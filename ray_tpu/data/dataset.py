"""Dataset: lazy plan over blocks, streaming per-operator execution.

Reference map (python/ray/data/):
  Dataset/logical plan        -> Dataset._ops list (dataset.py:385 map_batches)
  StreamingExecutor           -> ray_tpu.data.execution: a physical operator
                                 graph scheduled task-by-task against output
                                 byte budgets (streaming_executor_state.py:376
                                 select_operator_to_run); multi-op chains
                                 route through it, single-op chains keep the
                                 legacy fused windowed-generator path (the
                                 `fused` policy)
  DataIterator / train ingest -> DataIterator.iter_batches / split();
                                 per-host shard feeds via iter_split()
                                 (OutputSplitter over ONE executor run)
  datasources                 -> read_parquet/csv/json via pyarrow
"""

from __future__ import annotations

import builtins
import itertools
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Union)

import numpy as np

Block = Union[Dict[str, np.ndarray], list]

_DEFAULT_BLOCK_ROWS = 4096
_WINDOW = 4  # streaming shard tasks per iterator (execution parallelism)
_STREAM_AHEAD = 2  # blocks each shard executor may run ahead of consumption
_ADMISSION_FRACTION = 0.25  # share of the object store unconsumed blocks may hold


def _block_rows(b: Block) -> int:
    if isinstance(b, dict):
        return len(next(iter(b.values()))) if b else 0
    return len(b)


def _block_nbytes(b: Block) -> int:
    """Approximate in-memory bytes of a block — the unit the streaming
    executor's ResourceManager budgets (ref: BlockMetadata.size_bytes).
    Array columns are exact; object columns and list blocks estimate via
    per-item getsizeof."""
    import sys

    if isinstance(b, dict):
        total = 0
        for v in b.values():
            a = np.asarray(v)
            if a.dtype == object:
                total += int(sum(sys.getsizeof(x) for x in a.reshape(-1)))
            else:
                total += int(a.nbytes)
        return total
    if isinstance(b, list):
        return int(sum(sys.getsizeof(x) for x in b))
    return int(sys.getsizeof(b))


def _block_slice(b: Block, lo: int, hi: int) -> Block:
    if isinstance(b, dict):
        return {k: v[lo:hi] for k, v in b.items()}
    return b[lo:hi]


def _block_concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if _block_rows(b)]
    if not blocks:
        return []
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    out: list = []
    for b in blocks:
        out.extend(b)
    return out


def _arrow_to_block(table) -> Block:
    """Arrow table -> dict-of-numpy, ZERO-COPY per column when the type
    allows (numeric, single-chunk, no nulls — the same condition the
    reference's Arrow block accessor exploits for plasma reads); copies
    only the columns Arrow can't view (ref: data/_internal/arrow_block.py
    to_numpy path)."""
    out = {}
    for c in table.column_names:
        col = table[c]
        if col.num_chunks == 1:
            try:
                out[c] = col.chunk(0).to_numpy(zero_copy_only=True)
                continue
            except Exception:
                pass
        out[c] = col.to_numpy(zero_copy_only=False)
    return out


def _to_batch_format(block: Block, fmt: Optional[str]):
    """Present a block to a UDF in the requested format (ref:
    map_batches/iter_batches batch_format= in python/ray/data/dataset.py
    — "numpy"/"default" dict-of-ndarray, "pandas", "pyarrow")."""
    if fmt in (None, "default", "numpy"):
        return block
    if not isinstance(block, dict):
        block = _rows_to_block(block)
        if not isinstance(block, dict):
            block = {"value": np.asarray(block)}
    if fmt == "pandas":
        import pandas as pd

        return pd.DataFrame({k: (list(v) if getattr(v, "ndim", 1) > 1
                                 else v) for k, v in block.items()})
    if fmt == "pyarrow":
        import pyarrow as pa

        return pa.table({k: np.asarray(v) for k, v in block.items()})
    raise ValueError(f"unsupported batch_format {fmt!r}; "
                     "use 'numpy', 'pandas', or 'pyarrow'")


def _coerce_block(out) -> Block:
    """Normalize a UDF's return (dict / list / pa.Table / pd.DataFrame)
    back into a native block."""
    if isinstance(out, (dict, list)):
        return out
    mod = type(out).__module__
    if mod.startswith("pyarrow"):
        return _arrow_to_block(out)
    if mod.startswith("pandas"):
        cols = {}
        for c in out.columns:
            v = out[c].to_numpy()
            if v.dtype == object and len(v) and \
                    isinstance(v[0], np.ndarray):
                # 2-D column that rode through pandas as array-of-arrays
                # (see _to_batch_format's list(v) wrap) — restack it
                v = np.stack(v)
            cols[c] = v
        return cols
    raise TypeError(f"batch UDF returned unsupported type {type(out)}")


class _FormattedUDF:
    """Stateful-UDF wrapper adding batch_format conversion around a user
    class's __call__ (actor-pool map_batches with batch_format=)."""

    def __init__(self, cls, fmt, *args):
        self._inner = cls(*args)
        self._fmt = fmt

    def __call__(self, batch):
        return _coerce_block(self._inner(_to_batch_format(batch,
                                                          self._fmt)))


def _apply_op(block: Block, op: tuple) -> Block:
    kind, fn = op[0], op[1]
    if kind == "map_batches":
        # empty blocks skip the UDF on EVERY path: a fully-filtered
        # tabular block degrades to [] (schema lost), which a column-
        # addressing UDF cannot handle
        if _block_rows(block) == 0:
            return block
        return fn(block)
    if kind == "map":
        if isinstance(block, dict):
            rows = _rows_of(block)
            out = [fn(r) for r in rows]
            return _rows_to_block(out)
        return [fn(r) for r in block]
    if kind == "filter":
        if isinstance(block, dict):
            rows = _rows_of(block)
            out = [r for r in rows if fn(r)]
            return _rows_to_block(out)
        return [r for r in block if fn(r)]
    if kind == "flat_map":
        rows = _rows_of(block) if isinstance(block, dict) else block
        out: list = []
        for r in rows:
            out.extend(fn(r))
        return _rows_to_block(out) if isinstance(block, dict) else out
    raise ValueError(f"unknown op {kind}")


def _rows_of(block: Dict[str, np.ndarray]) -> List[dict]:
    keys = list(block.keys())
    n = _block_rows(block)
    return [{k: block[k][i] for k in keys} for i in builtins.range(n)]


def _rows_to_block(rows: List[Any]) -> Block:
    if rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        try:
            return {k: np.asarray([r[k] for r in rows]) for k in keys}
        except Exception:
            return rows
    return rows


def _transform_block(block: Block, ops: List[tuple]) -> Block:
    for op in ops:
        block = _apply_op(block, op)
    return block


def _apply_rebatched(fn, block: Block, bs: Optional[int]) -> Block:
    """Run fn over bs-row slices of the block and concat (shared by the
    task and actor-pool map_batches paths). Empty blocks (e.g. a filter
    matched nothing) skip the UDF — they also lose their column schema,
    so calling fn would hand it a bare list."""
    if _block_rows(block) == 0:
        return block
    if bs is None:
        return fn(block)
    n = _block_rows(block)
    outs = [fn(_block_slice(block, lo, min(lo + bs, n)))
            for lo in builtins.range(0, n, bs)]
    return _block_concat(outs)


class ActorPoolStrategy:
    """Stateful-actor compute for map_batches (ref: ActorPoolStrategy in
    data/_internal/compute.py). size actors each construct the UDF class
    once and stream blocks through it."""

    def __init__(self, size: int = 2, *, num_cpus_per_actor: float = 0.5,
                 min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        # min_size/max_size accepted for reference-API compatibility;
        # the pool is fixed-size (autoscaling pools are a later round)
        self.size = max_size or size
        self.num_cpus_per_actor = num_cpus_per_actor


class Dataset:
    """Immutable, lazy. Transformations append ops; execution happens on
    iteration/materialize via remote tasks over blocks. Exception:
    actor-pool map_batches stages (compute=ActorPoolStrategy / class
    UDFs) execute EAGERLY at call time — the pool's lifetime must bracket
    the pass (same shape as the reference's materialize-on-actor-pool
    paths)."""

    def __init__(self, block_refs: List[Any], ops: Optional[List[tuple]] = None):
        self._block_refs = block_refs
        self._ops = ops or []

    # ---- transformations (lazy) -------------------------------------------

    def map_batches(self, fn: Callable[[Block], Block], *,
                    batch_size: Optional[int] = None,
                    batch_format: Optional[str] = None,
                    compute: Optional["ActorPoolStrategy"] = None,
                    fn_constructor_args: tuple = ()) -> "Dataset":
        """batch_size re-slices each block before fn (ref: dataset.py:385
        map_batches(batch_size=...) — bounds the UDF's working set, e.g.
        a model's device batch). batch_format presents batches as
        "numpy" (default), "pandas", or "pyarrow" and accepts the same
        formats back (ref: map_batches(batch_format=...); Arrow
        conversion is zero-copy per column where types allow). A CLASS
        fn (or compute=ActorPoolStrategy(...)) runs on a pool of
        stateful actors so expensive setup — loading a model to the
        device — happens once per actor, not once per block (ref:
        _internal/execution/operators/actor_pool_map_operator.py)."""
        if batch_format not in (None, "default", "numpy",
                                "pandas", "pyarrow"):
            raise ValueError(f"unsupported batch_format {batch_format!r}; "
                             "use 'numpy', 'pandas', or 'pyarrow'")
        if batch_format not in (None, "default", "numpy"):
            fmt = batch_format
            if isinstance(fn, type):
                return self._map_batches_actors(
                    _FormattedUDF, batch_size,
                    compute or ActorPoolStrategy(),
                    (fn, fmt, *fn_constructor_args))
            user_fn = fn
            fn = lambda b: _coerce_block(user_fn(_to_batch_format(b, fmt)))
        if compute is not None or isinstance(fn, type):
            return self._map_batches_actors(
                fn, batch_size, compute or ActorPoolStrategy(),
                fn_constructor_args)
        if batch_size is None:
            return Dataset(self._block_refs,
                           self._ops + [("map_batches", fn)])
        return Dataset(
            self._block_refs,
            self._ops + [("map_batches",
                          lambda b: _apply_rebatched(fn, b, batch_size))])

    def _map_batches_actors(self, fn_cls, batch_size, strategy,
                            ctor_args) -> "Dataset":
        """Dispatch blocks over a pool of stateful map actors via the
        streaming executor's ActorPoolMapOperator; blocks travel as refs
        (never through the driver), dispatch/harvest ride the ordered
        ActorPool, and the pool is reaped at executor shutdown. Output
        block order matches input order."""
        from ray_tpu.data.execution import (ActorPoolMapOperator,
                                            InputDataBuffer,
                                            ResourceManager,
                                            StreamingExecutor, get_context)

        if not isinstance(fn_cls, type):
            raise TypeError(
                "compute=ActorPoolStrategy(...) needs a callable CLASS "
                "(stateful UDF with __call__), got a function")
        if not self._block_refs:
            return Dataset([], [])
        ctx = get_context()
        # pending lazy ops fuse INTO the actor (one hop per block, no
        # intermediate materialize through the store)
        n_actors = max(1, min(strategy.size, len(self._block_refs)))
        inp = InputDataBuffer(self._block_refs)
        op = ActorPoolMapOperator(
            "map_batches(actors)", fn_cls, tuple(ctor_args), n_actors,
            strategy.num_cpus_per_actor, batch_size,
            fused_ops=self._ops, input_op=inp)
        rm = ResourceManager([inp, op],
                             per_op_budget_bytes=ctx.per_op_budget_bytes)
        refs = StreamingExecutor([inp, op], rm).execute_to_refs()
        return Dataset(refs, [])

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [("map", fn)])

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [("filter", fn)])

    def flat_map(self, fn: Callable[[Any], Iterable[Any]]) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [("flat_map", fn)])

    # ---- execution ---------------------------------------------------------

    def _executed_refs(self) -> List[Any]:
        """Launch transform tasks for all blocks (full materialize path)."""
        import ray_tpu

        if not self._ops:
            return list(self._block_refs)
        ops = self._ops

        @ray_tpu.remote
        def _t(block):
            return _transform_block(block, ops)

        return [_t.remote(ref) for ref in self._block_refs]

    def materialize(self) -> "Dataset":
        import ray_tpu

        from ray_tpu.data.execution import build_pipeline, get_context

        pol = (get_context().resolve_policy(None, len(self._ops))
               if self._ops and self._block_refs else "fused")
        if pol in ("streaming", "compiled"):
            # budget-aware drain: transformed blocks land in the store in
            # source order; unconsumed bytes stay under the executor budget
            refs = build_pipeline(self._block_refs, self._ops,
                                  policy=pol).execute_to_refs()
            return Dataset(refs, [])
        refs = self._executed_refs()
        ray_tpu.wait(refs, num_returns=len(refs))
        return Dataset(refs, [])

    def iter_split(self, n: int) -> List["Iterator[Block]"]:
        """n in-process block iterators fed by ONE streaming-executor run
        (OutputSplitter sink, round-robin bundles) — the per-host shape of
        train ingest: one pipeline per host feeding that host's local
        consumers, instead of n disjoint pipelines (ref:
        output_splitter.py behind streaming_split). Consumers should be
        drained roughly together; a shard nobody reads parks its bundles
        in its queue. For cross-process per-rank ingest, use
        streaming_split() — its iterators pickle."""
        import ray_tpu

        from ray_tpu.data.execution import build_pipeline

        if not self._block_refs:
            return [iter(()) for _ in builtins.range(n)]
        executor = build_pipeline(self._block_refs, self._ops, split=n)

        def _blocks(shard):
            for bundle in shard:
                yield ray_tpu.get(bundle.block_ref)

        return [_blocks(s) for s in executor.execute_split(n)]

    def _iter_blocks(self, policy: Optional[str] = None) -> Iterator[Block]:
        """Streaming pull through one of two physical paths.

        `streaming` (default for chains of 2+ ops): the per-operator
        executor in ray_tpu.data.execution — every logical op becomes an
        independently scheduled operator, and select_operator_to_run
        keeps each operator's unconsumed output under a store-derived
        byte budget, so a slow late stage throttles the early stages
        (ref: streaming_executor_state.py:376).

        `fused` (default for single-op chains): _WINDOW generator tasks
        each transform a strided shard of the blocks with the whole
        chain fused, consumer-coupled generator backpressure keeps every
        executor at most _STREAM_AHEAD blocks ahead of consumption
        (ref: streaming generators). Round-robin over strided shards
        restores original block order. Both paths yield identical
        blocks in identical order."""
        import ray_tpu

        ops = self._ops
        if not ops:
            for ref in self._block_refs:
                yield ray_tpu.get(ref)
            return
        refs = self._block_refs
        if not refs:
            return
        from ray_tpu.data.execution import build_pipeline, get_context

        pol = get_context().resolve_policy(policy, len(ops))
        if pol in ("streaming", "compiled"):
            for bundle in build_pipeline(refs, ops, policy=pol).execute():
                yield ray_tpu.get(bundle.block_ref)
            return
        w = min(_WINDOW, len(refs))
        # Admission by object-store byte budget, not just block count
        # (ref: streaming_executor_state.py select_operator_to_run): all
        # shards together may hold at most ~ADMISSION_FRACTION of the
        # store in unconsumed blocks, so huge blocks throttle production
        # instead of spill-thrashing a small store.
        from ray_tpu.core import runtime as _rt

        r = _rt.current_runtime_or_none()
        store_budget = (r.cfg.object_store_memory if r is not None
                        else 2 << 30)
        frac = (r.cfg.data_execution_budget_fraction if r is not None
                else _ADMISSION_FRACTION)
        bp_bytes = max(1 << 20, int(store_budget * frac / w))

        @ray_tpu.remote(num_returns="streaming",
                        generator_backpressure=_STREAM_AHEAD,
                        generator_backpressure_bytes=bp_bytes)
        def _shard_t(shard_refs, ops):
            for r in shard_refs:
                yield _transform_block(ray_tpu.get(r), ops)

        active = [_shard_t.remote(refs[i::w], ops)
                  for i in builtins.range(w)]
        while active:
            exhausted = []
            for g in active:
                try:
                    ref = next(g)
                except StopIteration:
                    exhausted.append(g)
                    continue
                yield ray_tpu.get(ref)
            for g in exhausted:
                active.remove(g)

    # ---- consumption -------------------------------------------------------

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for block in self._iter_blocks():
            rows = _rows_of(block) if isinstance(block, dict) else block
            out.extend(rows[:n - len(out)])
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for block in self._iter_blocks():
            out.extend(_rows_of(block) if isinstance(block, dict) else block)
        return out

    def count(self) -> int:
        import ray_tpu

        if not self._ops:
            @ray_tpu.remote
            def _n(b):
                return _block_rows(b)

            return sum(ray_tpu.get([_n.remote(r) for r in self._block_refs]))
        return sum(_block_rows(b) for b in self._iter_blocks())

    def schema(self) -> Optional[List[str]]:
        for b in self._iter_blocks():
            if isinstance(b, dict):
                return list(b.keys())
            return None
        return None

    def columns(self) -> Optional[List[str]]:
        """Column names (ref: Dataset.columns — schema().names there)."""
        return self.schema()

    def take_batch(self, batch_size: int = 20,
                   batch_format: Optional[str] = None):
        """First up-to-batch_size rows as ONE batch (ref:
        Dataset.take_batch)."""
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format):
            return batch
        return _to_batch_format({}, batch_format)

    def iter_batches(self, *, batch_size: int = 256, drop_last: bool = False,
                     local_shuffle_seed: Optional[int] = None,
                     batch_format: Optional[str] = None):
        return DataIterator(self._block_refs, self._ops).iter_batches(
            batch_size=batch_size, drop_last=drop_last,
            local_shuffle_seed=local_shuffle_seed,
            batch_format=batch_format)

    def iter_torch_batches(self, **kw):
        return DataIterator(self._block_refs, self._ops).iter_torch_batches(
            **kw)

    def iter_rows(self) -> Iterator[Any]:
        for b in self._iter_blocks():
            yield from (_rows_of(b) if isinstance(b, dict) else b)

    # ---- reorganization ----------------------------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        import ray_tpu

        blocks = [b for b in self.materialize()._iter_blocks()]
        whole = _block_concat(blocks)
        n = _block_rows(whole)
        per = max(1, (n + num_blocks - 1) // num_blocks)
        refs = [ray_tpu.put(_block_slice(whole, i * per, min((i + 1) * per, n)))
                for i in builtins.range(num_blocks) if i * per < n]
        return Dataset(refs, [])

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        import ray_tpu

        rng = np.random.default_rng(seed)
        blocks = list(self.materialize()._iter_blocks())
        whole = _block_concat(blocks)
        n = _block_rows(whole)
        perm = rng.permutation(n)
        if isinstance(whole, dict):
            shuffled: Block = {k: v[perm] for k, v in whole.items()}
        else:
            shuffled = [whole[i] for i in perm]
        k = max(1, len(blocks))
        per = (n + k - 1) // k
        refs = [ray_tpu.put(_block_slice(shuffled, i * per,
                                         min((i + 1) * per, n)))
                for i in builtins.range(k) if i * per < n]
        return Dataset(refs, [])

    def split(self, n: int) -> List["Dataset"]:
        """Block-granularity split (ref: dataset.split)."""
        parts: List[List[Any]] = [[] for _ in builtins.range(n)]
        for i, ref in enumerate(self._block_refs):
            parts[i % n].append(ref)
        return [Dataset(p, list(self._ops)) for p in parts]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        """Row-exact split at sorted global indices (ref:
        dataset.split_at_indices)."""
        import ray_tpu

        if any(i < 0 for i in indices) or list(indices) != sorted(indices):
            raise ValueError(
                f"indices must be non-negative and sorted, got {indices}")
        whole = _block_concat(list(self._iter_blocks()))
        n = _block_rows(whole)
        bounds = [0] + [min(i, n) for i in indices] + [n]
        out = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            out.append(Dataset([ray_tpu.put(_block_slice(whole, lo, hi))],
                               []))
        return out

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> "tuple[Dataset, Dataset]":
        """(train, test) row split (ref: dataset.train_test_split)."""
        if not 0 < test_size < 1:
            raise ValueError("test_size must be in (0, 1)")
        # materialize once: count() + split would otherwise execute the
        # pending op pipeline twice (and disagree under nondeterminism)
        ds = (self.random_shuffle(seed=seed) if shuffle
              else self).materialize()
        n = ds.count()
        cut = n - int(n * test_size)
        train, test = ds.split_at_indices([cut])
        return train, test

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (ref: dataset.unique). Per-block
        np.unique runs in the transform tasks; only the small distinct
        sets reach the driver (same shape as preprocessors'
        _distributed_unique)."""
        def per_block(block):
            col = (block[column] if isinstance(block, dict)
                   else [r[column] for r in block])
            return {column: np.unique(np.asarray(col).reshape(-1))}

        seen: set = set()
        for block in self.map_batches(per_block)._iter_blocks():
            # blocks fully emptied by an upstream filter pass through
            # _apply_op untransformed as schemaless [] — nothing to add
            if _block_rows(block) == 0:
                continue
            for v in block[column]:
                seen.add(v.item() if hasattr(v, "item") else v)
        return sorted(seen)

    def show(self, limit: int = 20) -> None:
        """Print the first rows (ref: dataset.show)."""
        for r in self.take(limit):
            print(r)

    def streaming_split(self, n: int) -> List["DataIterator"]:
        """Per-rank iterators for train ingest (ref:
        stream_split_iterator.py)."""
        parts: List[List[Any]] = [[] for _ in builtins.range(n)]
        for i, ref in enumerate(self._block_refs):
            parts[i % n].append(ref)
        return [DataIterator(p, list(self._ops)) for p in parts]

    def groupby(self, key: str):
        """Two-stage distributed groupby (ref: dataset.groupby →
        grouped_data.py)."""
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sample-sort (ref: dataset.sort → sort exchange op in
        _internal/planner/exchange/sort_task_spec.py): sample keys to pick
        range boundaries, range-partition blocks in map tasks, sort each
        partition in reduce tasks."""
        import ray_tpu

        ops = self._ops
        refs = self._block_refs
        if not refs:
            return Dataset([], [])
        P = max(1, len(refs))

        @ray_tpu.remote
        def _sample(block):
            block = _transform_block(block, ops)
            if not isinstance(block, dict):
                block = _rows_to_block(block)
            if not isinstance(block, dict) or key not in block:
                return np.empty(0)   # block emptied by transforms
            col = np.asarray(block[key])
            if len(col) == 0:
                return col
            k = min(64, len(col))
            idx = np.random.default_rng(0).choice(len(col), size=k,
                                                  replace=False)
            return col[idx]

        sampled = [s for s in ray_tpu.get([_sample.remote(r) for r in refs])
                   if len(s)]
        if not sampled:   # every block empty after transforms
            return self.materialize()
        samples = np.concatenate(sampled)
        samples.sort()
        bounds = [samples[int(len(samples) * (i + 1) / P)]
                  for i in builtins.range(P - 1)]

        @ray_tpu.remote
        def _partition(block):
            block = _transform_block(block, ops)
            if not isinstance(block, dict):
                block = _rows_to_block(block)
            if not isinstance(block, dict) or key not in block:
                empty = {}
                return tuple(empty for _ in builtins.range(P)) \
                    if P > 1 else empty
            col = np.asarray(block[key])
            part_ids = np.searchsorted(np.asarray(bounds), col, side="right")
            out = []
            for p in builtins.range(P):
                idx = np.flatnonzero(part_ids == p)
                out.append({c: v[idx] for c, v in block.items()})
            return tuple(out) if P > 1 else out[0]

        @ray_tpu.remote
        def _sort_part(*subs):
            whole = _block_concat([b for b in subs if _block_rows(b)])
            if not _block_rows(whole):
                return {}
            order = np.argsort(np.asarray(whole[key]), kind="stable")
            if descending:
                order = order[::-1]
            return {c: v[order] for c, v in whole.items()}

        part_refs = [_partition.options(num_returns=P).remote(r)
                     if P > 1 else [_partition.remote(r)] for r in refs]
        out_refs = [_sort_part.remote(*[pr[p] for pr in part_refs])
                    for p in builtins.range(P)]
        if descending:
            out_refs = out_refs[::-1]
        return Dataset(out_refs, [])

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of equal-length datasets (ref: dataset.zip).
        Blocks stay in the object store: per-output-block merge tasks pull
        only the row ranges they need from the right side."""
        import ray_tpu

        a = self.materialize()
        b = other.materialize()

        @ray_tpu.remote
        def _rows(block):
            return _block_rows(block)

        na = ray_tpu.get([_rows.remote(r) for r in a._block_refs])
        nb = ray_tpu.get([_rows.remote(r) for r in b._block_refs])
        if sum(na) != sum(nb):
            raise ValueError("zip requires equal row counts")

        @ray_tpu.remote
        def _merge(left, lo, hi, *right_parts):
            """left block + the right-side row range [lo, hi) assembled
            from the overlapping right blocks."""
            right = _block_concat(list(right_parts))
            right = _block_slice(right, lo, hi)
            merged = dict(left) if isinstance(left, dict) else \
                {"_left": np.asarray(left)}
            rd = right if isinstance(right, dict) else \
                {"_right": np.asarray(right)}
            for c, v in rd.items():
                merged[c if c not in merged else f"{c}_1"] = v
            return merged

        # offsets of each right block in global row space
        b_starts = np.cumsum([0] + nb)
        out_refs = []
        pos = 0
        for ref, n in builtins.zip(a._block_refs, na):
            lo, hi = pos, pos + n
            # right blocks overlapping [lo, hi)
            first = int(np.searchsorted(b_starts, lo, side="right")) - 1
            last = int(np.searchsorted(b_starts, hi, side="left"))
            parts = b._block_refs[first:last]
            out_refs.append(_merge.remote(
                ref, lo - int(b_starts[first]),
                hi - int(b_starts[first]), *parts))
            pos = hi
        return Dataset(out_refs, [])

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join (ref: Dataset.join / join exchange op):
        hash-partition both sides on the key in map tasks, then one join
        task per partition pairs matching rows. Supports inner/left/right/
        outer; non-key columns colliding on name get a ``_1`` suffix on the
        right side, as the reference does."""
        import ray_tpu

        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join type {how!r}")
        P = num_partitions or max(len(self._block_refs),
                                  len(other._block_refs), 1)

        def _hash_partition(ops, key):
            @ray_tpu.remote
            def _part(block):
                block = _transform_block(block, ops)
                if not isinstance(block, dict):
                    block = _rows_to_block(block)
                if not isinstance(block, dict) or key not in block:
                    return tuple({} for _ in builtins.range(P)) \
                        if P > 1 else {}
                import zlib

                def _khash(v):
                    # crc32: stable across worker processes, unlike the
                    # salted builtin str hash. Integral floats normalize to
                    # int so 2 and 2.0 land in the same partition (they
                    # compare equal in the join task).
                    v = v.item() if hasattr(v, "item") else v
                    if isinstance(v, float) and v.is_integer():
                        v = int(v)
                    return zlib.crc32(str(v).encode()) % P

                col = np.asarray(block[key])
                pid = np.asarray([_khash(v) for v in col])
                out = []
                for p in builtins.range(P):
                    idx = np.flatnonzero(pid == p)
                    out.append({c: np.asarray(v)[idx]
                                for c, v in block.items()})
                return tuple(out) if P > 1 else out[0]

            return _part

        pa = _hash_partition(self._ops, on)
        pb = _hash_partition(other._ops, on)
        a_parts = [pa.options(num_returns=P).remote(r) if P > 1
                   else [pa.remote(r)] for r in self._block_refs]
        b_parts = [pb.options(num_returns=P).remote(r) if P > 1
                   else [pb.remote(r)] for r in other._block_refs]

        @ray_tpu.remote
        def _join_part(na, nb, *subs):
            left = _block_concat([s for s in subs[:na] if _block_rows(s)])
            right = _block_concat([s for s in subs[na:] if _block_rows(s)])
            lrows = _rows_of(left) if isinstance(left, dict) else []
            rrows = _rows_of(right) if isinstance(right, dict) else []
            rindex: Dict[Any, List[dict]] = {}
            for r in rrows:
                rindex.setdefault(np.asarray(r[on]).item(), []).append(r)
            # Column sets come from every source block's partition output
            # (not just this partition's non-empty rows), so fill columns
            # are stable even when one side is empty in this partition.
            def _cols(ds):
                cols: List[str] = []
                for d in ds:
                    if isinstance(d, dict):
                        for c in d.keys():
                            if c not in cols:
                                cols.append(c)
                return cols

            lcols = _cols(subs[:na])
            rcols = _cols(subs[na:])
            matched_r = set()
            out_rows: List[dict] = []
            for lr in lrows:
                k = np.asarray(lr[on]).item()
                matches = rindex.get(k, [])
                if matches:
                    matched_r.add(k)
                    for rr in matches:
                        row = dict(lr)
                        for c in rcols:
                            if c == on:
                                continue
                            row[c if c not in row else f"{c}_1"] = rr[c]
                        out_rows.append(row)
                elif how in ("left", "outer"):
                    row = dict(lr)
                    for c in rcols:
                        if c == on:
                            continue
                        row.setdefault(c if c not in lr else f"{c}_1",
                                       np.nan)
                    out_rows.append(row)
            if how in ("right", "outer"):
                for rr in rrows:
                    if np.asarray(rr[on]).item() in matched_r:
                        continue
                    # key always survives, even when this partition saw no
                    # left rows (lcols empty)
                    row = {on: rr[on]}
                    for c in lcols:
                        if c != on:
                            row[c] = np.nan
                    for c in rcols:
                        if c == on:
                            continue
                        row[c if c not in row else f"{c}_1"] = rr[c]
                    out_rows.append(row)
            return _rows_to_block(out_rows) if out_rows else {}

        out_refs = []
        for p in builtins.range(P):
            subs = [ap[p] for ap in a_parts] + [bp[p] for bp in b_parts]
            out_refs.append(_join_part.remote(len(a_parts), len(b_parts),
                                              *subs))
        return Dataset(out_refs, [])

    def limit(self, n: int) -> "Dataset":
        rows = self.take(n)
        from ray_tpu.data.dataset import _put_blocks

        return _put_blocks([_rows_to_block(rows)])

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]
                   ) -> "Dataset":
        def _add(block):
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return out

        return self.map_batches(_add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        drop = set(cols)
        return self.map_batches(
            lambda b: {c: v for c, v in b.items() if c not in drop})

    def select_columns(self, cols: List[str]) -> "Dataset":
        keep = list(cols)
        return self.map_batches(lambda b: {c: b[c] for c in keep})

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map_batches(
            lambda b: {mapping.get(c, c): v for c, v in b.items()})

    # ---- global aggregates -------------------------------------------------

    def _global_agg(self, agg) -> Any:
        import ray_tpu

        ops = self._ops

        @ray_tpu.remote
        def _partial(block):
            block = _transform_block(block, ops)
            if not isinstance(block, dict):
                col = np.asarray(block)
            else:
                col = np.asarray(block[agg.on]) if getattr(agg, "on", None) \
                    else next(iter(block.values()))
            return agg.accumulate_block(agg.init(), col)

        partials = ray_tpu.get(
            [_partial.remote(r) for r in self._block_refs])
        acc = agg.init()
        for p in partials:
            acc = agg.merge(acc, p)
        return agg.finalize(acc)

    def sum(self, on: str):
        from ray_tpu.data.aggregate import Sum

        return self._global_agg(Sum(on))

    def min(self, on: str):
        from ray_tpu.data.aggregate import Min

        return self._global_agg(Min(on))

    def max(self, on: str):
        from ray_tpu.data.aggregate import Max

        return self._global_agg(Max(on))

    def mean(self, on: str):
        from ray_tpu.data.aggregate import Mean

        return self._global_agg(Mean(on))

    def std(self, on: str, ddof: int = 1):
        from ray_tpu.data.aggregate import Std

        return self._global_agg(Std(on, ddof))

    def union(self, other: "Dataset") -> "Dataset":
        if self._ops or other._ops:
            a = self.materialize()
            b = other.materialize()
            return Dataset(a._block_refs + b._block_refs, [])
        return Dataset(self._block_refs + other._block_refs, [])

    # ---- output ------------------------------------------------------------

    def _write_files(self, path: str, ext: str, write_one) -> List[str]:
        """One write task per block → part-NNNNN.<ext> under `path`
        (ref: Dataset.write_parquet et al., file-per-block layout)."""
        import os

        import ray_tpu

        os.makedirs(path, exist_ok=True)
        ops = self._ops

        @ray_tpu.remote
        def _w(block, out_path):
            block = _transform_block(block, ops)
            if not isinstance(block, dict):
                block = _rows_to_block(block)
            if not isinstance(block, dict):
                block = {"value": np.asarray(block)}
            write_one(block, out_path)
            return out_path

        refs = [_w.remote(ref, os.path.join(path, f"part-{i:05d}.{ext}"))
                for i, ref in enumerate(self._block_refs)]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str) -> List[str]:
        def _one(block, out):
            import pyarrow as pa
            import pyarrow.parquet as pq

            pq.write_table(pa.table(block), out)

        return self._write_files(path, "parquet", _one)

    def write_csv(self, path: str) -> List[str]:
        def _one(block, out):
            import pyarrow as pa
            import pyarrow.csv as pc

            pc.write_csv(pa.table(block), out)

        return self._write_files(path, "csv", _one)

    def write_json(self, path: str) -> List[str]:
        def _one(block, out):
            import json as _json

            rows = _rows_of(block)
            with open(out, "w") as f:
                for r in rows:
                    f.write(_json.dumps(
                        {k: (v.item() if isinstance(v, np.generic)
                             else v.tolist() if isinstance(v, np.ndarray)
                             else v) for k, v in r.items()}) + "\n")

        return self._write_files(path, "json", _one)

    def to_pandas(self):
        import pandas as pd

        blocks = [b for b in self._iter_blocks() if _block_rows(b)]
        if not blocks:
            return pd.DataFrame()
        whole = _block_concat(blocks)
        if not isinstance(whole, dict):
            whole = _rows_to_block(whole)
            if not isinstance(whole, dict):
                whole = {"value": np.asarray(whole)}
        return pd.DataFrame(
            {k: list(v) if getattr(v, "ndim", 1) > 1 else v
             for k, v in whole.items()})

    def to_arrow(self):
        import pyarrow as pa

        whole = _block_concat(list(self._iter_blocks()))
        if not isinstance(whole, dict):
            whole = _rows_to_block(whole)
        return pa.table(whole)

    def stats(self) -> str:
        """Execution summary (ref: Dataset.stats())."""
        import ray_tpu

        @ray_tpu.remote
        def _n(b):
            return _block_rows(b)

        rows = ray_tpu.get([_n.remote(r) for r in self._block_refs])
        total = sum(rows)
        # Counts describe the stored source blocks; pending lazy ops (which
        # may change row counts, e.g. filter) run at materialization.
        kind = "source rows" if self._ops else "rows"
        return (f"Dataset: {len(self._block_refs)} blocks, {total} {kind} "
                f"(min {min(rows) if rows else 0} / "
                f"max {max(rows) if rows else 0} rows/block), "
                f"pending ops: {[o[0] for o in self._ops]}")

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._block_refs)}, "
                f"ops={[o[0] for o in self._ops]})")


class DataIterator:
    """Picklable per-rank iterator: holds block refs + pending ops and pulls
    through `_iter_blocks` in the consumer process — i.e. multi-op train
    ingest rides the streaming executor on each rank automatically
    (ref: DataIterator, iterator.py; train ingest session.py:901)."""

    def __init__(self, block_refs: List[Any], ops: List[tuple]):
        self._block_refs = block_refs
        self._ops = ops

    def __reduce__(self):
        return (DataIterator, (self._block_refs, self._ops))

    def _dataset(self) -> Dataset:
        return Dataset(self._block_refs, self._ops)

    def iter_batches(self, *, batch_size: int = 256, drop_last: bool = False,
                     local_shuffle_seed: Optional[int] = None,
                     batch_format: Optional[str] = None):
        rng = (np.random.default_rng(local_shuffle_seed)
               if local_shuffle_seed is not None else None)
        buf: List[Block] = []
        rows_in_buf = 0
        for block in self._dataset()._iter_blocks():
            buf.append(block)
            rows_in_buf += _block_rows(block)
            while rows_in_buf >= batch_size:
                whole = _block_concat(buf)
                if rng is not None:
                    n = _block_rows(whole)
                    perm = rng.permutation(n)
                    if isinstance(whole, dict):
                        whole = {k: v[perm] for k, v in whole.items()}
                    else:
                        whole = [whole[i] for i in perm]
                batch = _block_slice(whole, 0, batch_size)
                rest = _block_slice(whole, batch_size, _block_rows(whole))
                buf = [rest]
                rows_in_buf = _block_rows(rest)
                yield _to_batch_format(batch, batch_format)
        if rows_in_buf and not drop_last:
            yield _to_batch_format(_block_concat(buf), batch_format)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           local_shuffle_seed: Optional[int] = None,
                           dtypes=None, device=None):
        """Batches as torch tensors (ref: iterator.py iter_torch_batches —
        the reference's torch-ingest path; torch-cpu is in the TPU image
        for migration workloads). Tabular blocks become {col: tensor};
        list blocks become a tensor when rows are numeric."""
        import torch

        def to_t(v, col=None):
            t = torch.as_tensor(np.asarray(v))
            dt = dtypes.get(col) if isinstance(dtypes, dict) else dtypes
            if dt is not None:
                t = t.to(dt)
            if device is not None:
                t = t.to(device)
            return t

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last,
                                       local_shuffle_seed=local_shuffle_seed):
            if isinstance(batch, dict):
                yield {k: to_t(v, k) for k, v in batch.items()}
            else:
                yield to_t(batch)

    def iter_device_batches(self, *, batch_size: int, sharding=None,
                            drop_last: bool = True):
        """Double-buffered device feed: batch i+1 transfers to HBM while the
        step consumes batch i (SURVEY.md §7.7 device-side prefetch)."""
        import jax

        def put(b):
            if sharding is not None:
                return jax.device_put(b, sharding)
            return jax.device_put(b)

        it = self.iter_batches(batch_size=batch_size, drop_last=drop_last)
        prev = None
        for batch in it:
            cur = put(batch)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev


# --- creation ---------------------------------------------------------------


def _put_blocks(blocks: List[Block]) -> Dataset:
    import ray_tpu

    return Dataset([ray_tpu.put(b) for b in blocks], [])


def range(n: int, *, num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    k = num_blocks or max(1, min(64, n // _DEFAULT_BLOCK_ROWS or 1))
    per = (n + k - 1) // k
    blocks = []
    i = 0
    while i * per < n:
        blocks.append({"id": np.arange(i * per, min((i + 1) * per, n))})
        i += 1
    return _put_blocks(blocks)


def from_items(items: Sequence[Any], *, num_blocks: int = 8) -> Dataset:
    items = list(items)
    k = max(1, min(num_blocks, len(items) or 1))
    per = (len(items) + k - 1) // k
    blocks = []
    i = 0
    while i * per < len(items):
        blocks.append(items[i * per:(i + 1) * per])
        i += 1
    return _put_blocks([_rows_to_block(b) for b in blocks])


def from_numpy(arrays: Dict[str, np.ndarray], *, num_blocks: int = 8) -> Dataset:
    n = len(next(iter(arrays.values())))
    k = max(1, min(num_blocks, n))
    per = (n + k - 1) // k
    blocks = []
    i = 0
    while i * per < n:
        blocks.append({key: v[i * per:(i + 1) * per]
                       for key, v in arrays.items()})
        i += 1
    return _put_blocks(blocks)


def from_pandas(df, *, num_blocks: int = 8) -> Dataset:
    return from_numpy({c: df[c].to_numpy() for c in df.columns},
                      num_blocks=num_blocks)


def _read_files(paths, reader) -> Dataset:
    import glob as _glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(_glob.glob(os.path.join(p, "*"))))
        else:
            files.extend(sorted(_glob.glob(p)) or [p])
    import ray_tpu

    @ray_tpu.remote
    def _read(path: str):
        return reader(path)

    return Dataset([_read.remote(f) for f in files], [])


def read_parquet(paths) -> Dataset:
    def reader(path):
        import pyarrow.parquet as pq

        t = pq.read_table(path)
        return _arrow_to_block(t)

    return _read_files(paths, reader)


def read_csv(paths) -> Dataset:
    def reader(path):
        import pyarrow.csv as pc

        t = pc.read_csv(path)
        return _arrow_to_block(t)

    return _read_files(paths, reader)


def read_json(paths) -> Dataset:
    def reader(path):
        import pyarrow.json as pj

        t = pj.read_json(path)
        return _arrow_to_block(t)

    return _read_files(paths, reader)


def read_text(paths) -> Dataset:
    """One row per line: {"text": str} (ref: read_api.read_text)."""
    def reader(path):
        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        return {"text": np.asarray(lines, dtype=object)}

    return _read_files(paths, reader)


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    """One row per file: {"bytes": ...} (ref: read_api.read_binary_files)."""
    def reader(path):
        with open(path, "rb") as f:
            data = f.read()
        row = {"bytes": np.asarray([data], dtype=object)}
        if include_paths:
            row["path"] = np.asarray([path], dtype=object)
        return row

    return _read_files(paths, reader)


def read_images(paths, *, size=None, mode: Optional[str] = None) -> Dataset:
    """Decode images with PIL into {"image": HxWxC uint8}
    (ref: datasource/image_datasource.py)."""
    def reader(path):
        from PIL import Image

        im = Image.open(path)
        if mode:
            im = im.convert(mode)
        if size:
            im = im.resize(tuple(size))
        arr = np.asarray(im)
        return {"image": arr[None, ...]}

    return _read_files(paths, reader)


def _parse_tfrecord_example(buf: bytes) -> Dict[str, Any]:
    """Minimal protobuf wire parse of tf.train.Example — enough to round-trip
    Int64List/FloatList/BytesList features without a TF dependency
    (ref: datasource/tfrecords_datasource.py, which uses tf.train.Example)."""
    import struct

    def varint(b, i):
        x = s = 0
        while True:
            c = b[i]
            x |= (c & 0x7F) << s
            i += 1
            if not c & 0x80:
                return x, i
            s += 7

    def fields(b):
        i = 0
        while i < len(b):
            tag, i = varint(b, i)
            fnum, wt = tag >> 3, tag & 7
            if wt == 0:
                v, i = varint(b, i)
            elif wt == 2:
                ln, i = varint(b, i)
                v = b[i:i + ln]
                i += ln
            elif wt == 5:
                v = b[i:i + 4]
                i += 4
            elif wt == 1:
                v = b[i:i + 8]
                i += 8
            else:
                raise ValueError(f"wire type {wt}")
            yield fnum, wt, v

    out: Dict[str, Any] = {}
    for fnum, _, features in fields(buf):     # Example.features = 1
        if fnum != 1:
            continue
        for fn2, _, entry in fields(features):  # Features.feature = 1 (map)
            if fn2 != 1:
                continue
            key, feat = None, b""
            for fn3, _, v in fields(entry):
                if fn3 == 1:
                    key = v.decode()
                elif fn3 == 2:
                    feat = v
            if key is None:
                continue
            for fn4, wt4, flist in fields(feat):  # Feature oneof
                vals: List[Any] = []
                for fn5, wt5, v in fields(flist):  # *List.value = 1
                    if fn5 != 1:
                        continue
                    if fn4 == 1:                 # BytesList
                        vals.append(v)
                    elif fn4 == 2:               # FloatList
                        if wt5 == 2:             # packed
                            vals.extend(struct.unpack(
                                f"<{len(v) // 4}f", v))
                        else:
                            vals.append(struct.unpack("<f", v)[0])
                    elif fn4 == 3:               # Int64List
                        def _signed(x):
                            # proto int64 negatives arrive as 10-byte
                            # varints; fold back to two's complement
                            return x - (1 << 64) if x >= 1 << 63 else x

                        if wt5 == 2:             # packed varints
                            j = 0
                            while j < len(v):
                                x, j = varint(v, j)
                                vals.append(_signed(x))
                        else:
                            vals.append(_signed(v))
                out[key] = vals[0] if len(vals) == 1 else vals
    return out


def read_tfrecords(paths) -> Dataset:
    """TFRecord container framing is public and simple: per record
    {u64 length, u32 masked-crc(length), bytes, u32 masked-crc(data)};
    payloads are tf.train.Example protos parsed by the wire-level reader
    above. CRCs are not verified (matches the reference's default)."""
    def reader(path):
        import struct

        rows: List[dict] = []
        with open(path, "rb") as f:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    break
                (length,) = struct.unpack("<Q", hdr)
                f.read(4)
                data = f.read(length)
                if len(data) < length:
                    raise ValueError(
                        f"truncated TFRecord in {path}: record claims "
                        f"{length} bytes, file ends after {len(data)}")
                f.read(4)
                rows.append(_parse_tfrecord_example(data))
        return _rows_to_block(rows)

    return _read_files(paths, reader)


def from_arrow(table, *, num_blocks: int = 8) -> Dataset:
    """Arrow table -> Dataset; numeric columns become zero-copy numpy
    views over the Arrow buffers (ref: from_arrow in read_api.py; the
    copy happens only at the object-store put, as in the reference)."""
    return from_numpy(_arrow_to_block(table), num_blocks=num_blocks)


def read_sql(sql: str, connection_factory, *,
             parallelism: int = 1) -> Dataset:
    """Read query results into a Dataset (ref: datasource/sql_datasource.py
    — any DBAPI2 connection factory; sqlite3 in-image, client libraries
    for other engines plug in the same way). `parallelism` splits with
    LIMIT/OFFSET pagination when > 1 (same strategy as the reference)."""
    import ray_tpu

    @ray_tpu.remote
    def _query(page: Optional[Tuple[int, int]]):
        conn = connection_factory()
        try:
            cur = conn.cursor()
            if page is None:
                cur.execute(sql)
            else:
                # integers inlined (no driver paramstyle dependency) and
                # the derived table aliased (PostgreSQL/MySQL require it)
                off, lim = int(page[0]), int(page[1])
                cur.execute(f"SELECT * FROM ({sql}) AS _rt_page "
                            f"LIMIT {lim} OFFSET {off}")
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        return {c: np.asarray([r[i] for r in rows])
                for i, c in enumerate(cols)}

    if parallelism <= 1:
        refs = [_query.remote(None)]
    else:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT COUNT(*) FROM ({sql}) AS _rt_count")
            (total,) = cur.fetchone()
        finally:
            conn.close()
        per = max((total + parallelism - 1) // parallelism, 1)
        refs = [_query.remote((off, per))
                for off in builtins.range(0, max(total, 1), per)]
    return Dataset(refs, [])


def write_sql(ds: "Dataset", table: str, connection_factory,
              *, if_exists: str = "append") -> int:
    """Write a Dataset into a DBAPI2 table; returns rows written
    (ref: Dataset.write_sql)."""
    import ray_tpu

    total = 0
    blocks = ray_tpu.get(ds._executed_refs())
    conn = connection_factory()
    try:
        cur = conn.cursor()
        first = True
        for block in blocks:
            if not isinstance(block, dict):
                block = _rows_to_block(block)
            if not isinstance(block, dict) or not block:
                continue  # block emptied by transforms
            cols = list(block)
            n = len(block[cols[0]])
            if n == 0:
                continue
            if first and if_exists == "replace":
                cur.execute(f"DROP TABLE IF EXISTS {table}")
            if first:
                decls = ", ".join(f'"{c}"' for c in cols)
                cur.execute(
                    f"CREATE TABLE IF NOT EXISTS {table} ({decls})")
                first = False
            ph = ", ".join("?" * len(cols))
            rows = [tuple(_py_scalar(block[c][i]) for c in cols)
                    for i in builtins.range(n)]
            cur.executemany(f"INSERT INTO {table} VALUES ({ph})", rows)
            total += n
        conn.commit()
    finally:
        conn.close()
    return total


def _py_scalar(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def read_webdataset(paths) -> Dataset:
    """WebDataset-style tar shards: files grouped by basename stem, one
    row per sample keyed by extension (ref: datasource/webdataset_datasource.py;
    the format itself is just POSIX tar, stdlib-readable)."""
    def reader(path):
        import tarfile

        samples: Dict[str, dict] = {}
        order: List[str] = []
        with tarfile.open(path, "r") as tf:
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                stem, _, ext = m.name.partition(".")
                if stem not in samples:
                    samples[stem] = {"__key__": stem}
                    order.append(stem)
                data = tf.extractfile(m).read()
                if ext in ("txt", "cls", "json"):
                    data = data.decode("utf-8", errors="replace")
                    if ext == "json":
                        import json as _json

                        data = _json.loads(data)
                samples[stem][ext] = data
        return _rows_to_block([samples[k] for k in order])

    return _read_files(paths, reader)


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[dict]] = None,
               parallelism: int = 1) -> Dataset:
    """Read a MongoDB collection (ref: datasource/mongo_datasource.py —
    pymongo there too; parallel reads partition on `_id` ranges).
    Gated: pymongo is not in the TPU image."""
    try:
        import pymongo  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_mongo needs the pymongo package, which is not in the "
            "TPU image; install it in your driver/worker environment"
        ) from e
    import ray_tpu

    @ray_tpu.remote
    def _read(shard: int):
        import pymongo

        client = pymongo.MongoClient(uri)
        coll = client[database][collection]
        stages = list(pipeline or [])
        if parallelism > 1:
            # shard on a hash of _id (works for ObjectId AND scalar _id
            # types; a timestamp-derived key would be second-granular —
            # every ObjectId's ms value is a multiple of 1000, starving
            # shards whenever parallelism shares a factor with 1000)
            stages.insert(0, {"$match": {"$expr": {"$eq": [
                {"$mod": [{"$abs": {"$toHashedIndexKey": "$_id"}},
                          parallelism]}, shard]}}})
        rows = []
        for doc in coll.aggregate(stages) if stages else coll.find():
            doc.pop("_id", None)
            rows.append(doc)
        client.close()
        return _rows_to_block(rows)

    return Dataset([_read.remote(i) for i in builtins.range(parallelism)],
                   [])


def read_bigquery(query: str, *, project: Optional[str] = None) -> Dataset:
    """Read BigQuery results (ref: datasource/bigquery_datasource.py).
    Gated: google-cloud-bigquery is not in the TPU image."""
    try:
        from google.cloud import bigquery  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_bigquery needs the google-cloud-bigquery package, "
            "which is not in the TPU image; install it in your driver "
            "environment") from e
    import ray_tpu

    @ray_tpu.remote
    def _read():
        from google.cloud import bigquery as bq

        client = bq.Client(project=project)
        table = client.query(query).to_arrow()
        return _arrow_to_block(table)

    return Dataset([_read.remote()], [])
