"""GroupedData: two-stage distributed groupby.

Reference: python/ray/data/grouped_data.py + the map/reduce exchange in
_internal/planner/exchange/ — stage 1 runs per-block partial aggregation
(or hash partitioning for map_groups) as parallel tasks; stage 2 merges
partials (aggregate) or applies the UDF per key partition (map_groups).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.data.aggregate import (AggregateFn, Count, Max, Mean, Min, Std,
                                    Sum)


def _stable_hash(k) -> int:
    """Process-independent key hash (built-in str hash is seeded per
    process, which would scatter one key across reduce partitions)."""
    import zlib

    return zlib.crc32(repr(k).encode())


def _group_indices(keycol: np.ndarray) -> Dict[Any, np.ndarray]:
    order = np.argsort(keycol, kind="stable")
    skeys = keycol[order]
    bounds = np.flatnonzero(skeys[1:] != skeys[:-1]) + 1
    splits = np.split(order, bounds)
    # each split holds indices into the ORIGINAL keycol
    return {keycol[s[0]]: s for s in splits if len(s)}


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    # ---- aggregate ---------------------------------------------------------

    def aggregate(self, *aggs: AggregateFn):
        """Returns a Dataset of one row per key with aggregate columns."""
        import ray_tpu
        from ray_tpu.data import dataset as D

        key = self._key
        ops = self._ds._ops

        @ray_tpu.remote
        def _partial(block):
            block = D._transform_block(block, ops)
            if not isinstance(block, dict):
                block = D._rows_to_block(block)
            if not isinstance(block, dict) or key not in block:
                return {}
            keycol = np.asarray(block[key])
            out: Dict[Any, list] = {}
            for k, idx in _group_indices(keycol).items():
                states = []
                for agg in aggs:
                    col = block[agg.on][idx] if getattr(agg, "on", None) \
                        else keycol[idx]
                    states.append(agg.accumulate_block(agg.init(), col))
                out[k] = states
            return out

        partials = ray_tpu.get(
            [_partial.remote(r) for r in self._ds._block_refs])
        merged: Dict[Any, list] = {}
        for p in partials:
            for k, states in p.items():
                if k not in merged:
                    merged[k] = states
                else:
                    merged[k] = [agg.merge(a, b) for agg, a, b
                                 in zip(aggs, merged[k], states)]
        keys = sorted(merged.keys())
        cols: Dict[str, np.ndarray] = {key: np.asarray(keys)}
        for j, agg in enumerate(aggs):
            cols[agg.name] = np.asarray(
                [agg.finalize(merged[k][j]) for k in keys])
        return D.from_numpy(cols, num_blocks=1)

    def count(self):
        return self.aggregate(Count())

    def sum(self, on: str):
        return self.aggregate(Sum(on))

    def min(self, on: str):
        return self.aggregate(Min(on))

    def max(self, on: str):
        return self.aggregate(Max(on))

    def mean(self, on: str):
        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof))

    # ---- map_groups --------------------------------------------------------

    def map_groups(self, fn: Callable[[dict], Any], *,
                   num_partitions: int = 8):
        """Hash-partition rows by key across tasks, then apply fn per group
        (ref: grouped_data.py map_groups → sort-based shuffle)."""
        import ray_tpu
        from ray_tpu.data import dataset as D

        key = self._key
        ops = self._ds._ops
        P = num_partitions

        @ray_tpu.remote
        def _partition(block):
            block = D._transform_block(block, ops)
            if not isinstance(block, dict):
                block = D._rows_to_block(block)
            if not isinstance(block, dict) or key not in block:
                return tuple({} for _ in range(P))
            keycol = np.asarray(block[key])
            hashes = np.asarray([_stable_hash(k) % P
                                 for k in keycol.tolist()])
            parts = []
            for p in range(P):
                idx = np.flatnonzero(hashes == p)
                parts.append({c: v[idx] for c, v in block.items()})
            return tuple(parts)

        @ray_tpu.remote
        def _reduce(*sub_blocks):
            whole = D._block_concat([b for b in sub_blocks
                                     if D._block_rows(b)])
            if not D._block_rows(whole):
                return []
            keycol = np.asarray(whole[key])
            out = []
            for k, idx in _group_indices(keycol).items():
                group = {c: v[idx] for c, v in whole.items()}
                res = fn(group)
                if isinstance(res, list):
                    out.extend(res)
                else:
                    out.append(res)
            return D._rows_to_block(out)

        part_refs = [_partition.options(num_returns=P).remote(r)
                     for r in self._ds._block_refs]
        # part_refs[i] is a list of P refs (one per partition)
        out_refs = []
        for p in range(P):
            ins = [refs[p] for refs in part_refs]
            out_refs.append(_reduce.remote(*ins))
        return D.Dataset(out_refs, [])
