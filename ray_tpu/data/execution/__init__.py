"""ray_tpu.data.execution: the streaming, budget-aware executor.

The Data layer's physical execution engine (reference:
python/ray/data/_internal/execution/). A Dataset's logical op chain
compiles into a linear graph of PhysicalOperators — InputDataBuffer ->
map operators (task pool or actor pool) [-> OutputSplitter] — whose
queues carry block REFS + byte-size metadata, never blocks. The
StreamingExecutor's select_operator_to_run policy issues each next task
to the operator whose output queue is under a store-derived byte budget
(ResourceManager), so a slow downstream stage rate-limits its producers
instead of letting them flood the object store, while liveness rules
guarantee an idle pipeline always schedules.

`build_pipeline` is the compiler from (block_refs, logical ops) to a
ready StreamingExecutor; Dataset._iter_blocks / materialize /
_map_batches_actors / iter_split route through it. The legacy fused
path (one generator task per shard running the whole chain) survives as
the `fused` policy, the default for single-op chains.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ray_tpu.data.execution.compiled_map import CompiledChainMapOperator
from ray_tpu.data.execution.context import DataContext, get_context
from ray_tpu.data.execution.interfaces import (BlockMeta, OpBuffer,
                                               OpMetrics, PhysicalOperator,
                                               RefBundle)
from ray_tpu.data.execution.operators import (ActorPoolMapOperator,
                                              InputDataBuffer,
                                              OutputSplitter,
                                              TaskPoolMapOperator)
from ray_tpu.data.execution.resource_manager import (ResourceManager,
                                                     derive_budget_bytes)
from ray_tpu.data.execution.streaming_executor import (
    StreamingExecutor, get_last_execution_stats)


def build_pipeline(block_refs: List[Any], logical_ops: List[tuple],
                   *, split: Optional[int] = None,
                   context: Optional[DataContext] = None,
                   policy: Optional[str] = None
                   ) -> StreamingExecutor:
    """Compile a Dataset plan into a StreamingExecutor: one
    TaskPoolMapOperator per logical op (each independently scheduled —
    that's the cross-operator pipelining), plus an optional
    OutputSplitter sink for per-host shard iterators. Under
    policy="compiled" the whole chain fuses into one
    CompiledChainMapOperator riding standing channels instead."""
    ctx = context or get_context()
    max_in_flight = ctx.resolved_max_tasks_per_op()
    ops: List[PhysicalOperator] = [InputDataBuffer(block_refs)]
    if policy == "compiled" and logical_ops:
        from ray_tpu.data.execution.compiled_map import \
            CompiledChainMapOperator

        name = "+".join(spec[0] for spec in logical_ops)
        ops.append(CompiledChainMapOperator(
            name, logical_ops, ops[-1],
            pool_size=ctx.compiled_pool_size,
            max_in_flight=max_in_flight))
    else:
        for spec in logical_ops:
            ops.append(TaskPoolMapOperator(
                spec[0], [spec], ops[-1], max_in_flight=max_in_flight))
    if split is not None:
        ops.append(OutputSplitter(ops[-1], split))
    rm = ResourceManager(
        ops,
        total_budget_bytes=(derive_budget_bytes(ctx.budget_fraction)
                            if ctx.per_op_budget_bytes is None else None),
        per_op_budget_bytes=ctx.per_op_budget_bytes)
    return StreamingExecutor(ops, rm)


__all__ = [
    "ActorPoolMapOperator", "BlockMeta", "CompiledChainMapOperator",
    "DataContext", "InputDataBuffer",
    "OpBuffer", "OpMetrics", "OutputSplitter", "PhysicalOperator",
    "RefBundle", "ResourceManager", "StreamingExecutor", "build_pipeline",
    "derive_budget_bytes", "get_context", "get_last_execution_stats",
]
