"""DataContext: process-wide knobs for the streaming executor.

Reference: python/ray/data/context.py (DataContext.get_current) — a
singleton the Dataset execution paths consult, overridable per test or
per workload without threading parameters through every API.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

_POLICIES = ("auto", "fused", "streaming", "compiled")


@dataclass
class DataContext:
    #: "auto" (fused for single-op chains, streaming otherwise),
    #: "fused" (the legacy windowed generator path), "streaming", or
    #: "compiled" (whole chain fused onto a compiled-graph actor pool —
    #: standing channels, no per-block task dispatch; opt-in, never
    #: chosen by "auto")
    execution_policy: str = "auto"
    #: overrides Config.data_execution_budget_fraction when set
    budget_fraction: Optional[float] = None
    #: exact per-operator output budget (bytes); wins over the fraction
    per_op_budget_bytes: Optional[int] = None
    #: max concurrent tasks per operator (None -> Config value)
    max_tasks_per_op: Optional[int] = None
    #: actor-pool width for the "compiled" policy's fused chain operator
    compiled_pool_size: int = 2

    _current: "Optional[DataContext]" = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = cls()
        return cls._current

    def resolve_policy(self, explicit: Optional[str],
                       num_ops: int) -> str:
        pol = explicit or self.execution_policy
        if pol not in _POLICIES:
            raise ValueError(f"unknown execution policy {pol!r}; "
                             f"use one of {_POLICIES}")
        if pol == "auto":
            return "streaming" if num_ops > 1 else "fused"
        return pol

    def resolved_max_tasks_per_op(self) -> int:
        if self.max_tasks_per_op is not None:
            return self.max_tasks_per_op
        from ray_tpu.core import runtime as rt

        r = rt.current_runtime_or_none()
        return (r.cfg.data_execution_max_tasks_per_op if r is not None
                else 4)


def get_context() -> DataContext:
    return DataContext.get_current()
