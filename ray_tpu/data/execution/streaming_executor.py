"""StreamingExecutor: the cross-operator, budget-aware scheduling loop.

Reference map (python/ray/data/_internal/execution/):
  streaming_executor.py      -> the scheduling loop itself
  streaming_executor_state.py -> per-round state: poll completions, move
                                bundles, pick the next operator

The executor is a cooperative generator driven by the consumer: each
`next()` polls every operator for finished tasks, hands out as many new
tasks as the ResourceManager admits, and yields the sink's next bundle.
Consumer demand IS the outermost backpressure — when the training loop
stops pulling, task issue stops within one budget window.

Every run records a bounded trace of per-round operator states
(in-flight, queued bytes) and publishes a summary via
get_last_execution_stats() for tests and bench.py --bench data.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional

from ray_tpu.data.execution.interfaces import PhysicalOperator, RefBundle
from ray_tpu.data.execution.resource_manager import ResourceManager
from ray_tpu.observability import health as _health

# A scheduling round that admits nothing while work is in flight is
# normal backpressure; one that stays that way this long without any
# completion is a stalled pipeline (dead worker, wedged compiled op).
_STALL_DEADLINE_S = 60.0

_TRACE_CAP = 20_000
_LAST_STATS: Optional[Dict[str, Any]] = None


def get_last_execution_stats() -> Optional[Dict[str, Any]]:
    """Summary of the most recently finished executor run in this
    process: per-op metrics, peak queued bytes, round trace."""
    return _LAST_STATS


class StreamingExecutor:
    def __init__(self, operators: List[PhysicalOperator],
                 resource_manager: Optional[ResourceManager] = None):
        if not operators:
            raise ValueError("executor needs at least one operator")
        self._ops = operators
        self._rm = resource_manager or ResourceManager(operators)
        self._started = False
        self._shut = False
        self._beacon = _health.beacon("data:executor", _STALL_DEADLINE_S)
        self.trace: List[Dict[str, Any]] = []
        self.peak_queued_bytes = 0
        self.max_concurrent_ops = 0   # ops with in-flight tasks at once

    # --- lifecycle -----------------------------------------------------------

    def _start(self) -> None:
        if not self._started:
            self._started = True
            self._t_start = time.time()
            for op in self._ops:
                op.start()

    def shutdown(self) -> None:
        if self._shut:
            return
        self._shut = True
        self._beacon.disarm()
        for op in self._ops:
            try:
                op.shutdown()
            except Exception:
                pass
        self._publish_stats()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass

    def done(self) -> bool:
        return all(op.completed() for op in self._ops)

    # --- the scheduling round ------------------------------------------------

    def _step(self) -> bool:
        """One round: harvest completions, submit while the policy admits,
        then (if idle) block briefly on in-flight work."""
        import ray_tpu

        progressed = False
        for op in self._ops:
            if op.poll():
                progressed = True
        while True:
            op = self._rm.select_operator_to_run(self._ops)
            if op is None:
                break
            op.submit_next()
            progressed = True
        self._record_round()
        if progressed:
            self._beacon.tick()
            self._beacon.disarm()
        elif any(op.num_in_flight() > 0 for op in self._ops) \
                and not self._beacon.busy:
            self._beacon.arm(ops=[op.name for op in self._ops
                                  if op.num_in_flight() > 0])
        if not progressed:
            refs: List[Any] = []
            for op in self._ops:
                refs.extend(op.watch_refs())
            if refs:
                ray_tpu.wait(refs, num_returns=1, timeout=0.1)
            elif any(op.num_in_flight() > 0 for op in self._ops):
                # compiled-graph operators track in-flight work as
                # channel refs, not ObjectRefs — nothing to wait() on
                time.sleep(0.01)
            elif not self.done():
                # structurally unreachable: bundles are always in some
                # queue, making an operator input-ready, and an idle
                # pipeline always admits (ResourceManager liveness rule)
                raise RuntimeError(
                    "streaming executor stalled with no in-flight work: "
                    + ", ".join(repr(op) for op in self._ops))
        return progressed

    def _record_round(self) -> None:
        busy = sum(1 for op in self._ops if op.num_in_flight() > 0)
        self.max_concurrent_ops = max(self.max_concurrent_ops, busy)
        total_queued = sum(op.queued_output_bytes() for op in self._ops)
        self.peak_queued_bytes = max(self.peak_queued_bytes, total_queued)
        if len(self.trace) < _TRACE_CAP:
            self.trace.append({
                "t": time.monotonic(),
                "ops": [{"name": op.name,
                         "in_flight": op.num_in_flight(),
                         "queued_bytes": op.queued_output_bytes()}
                        for op in self._ops],
            })

    def _publish_stats(self) -> None:
        global _LAST_STATS
        _LAST_STATS = {
            "operators": {f"{op.depth}:{op.name}": op.metrics.as_dict()
                          for op in self._ops},
            "peak_queued_bytes": self.peak_queued_bytes,
            "max_concurrent_ops": self.max_concurrent_ops,
            "per_op_budget_bytes": self._rm.per_op_budget,
            "rounds": len(self.trace),
            "trace": self.trace,
        }
        # op-lifetime spans onto the unified timeline (no-op unless
        # tracing is on): one `data::<op>` slice per operator covering
        # the run, with its metrics as span attributes
        from ray_tpu.util import tracing
        t0 = getattr(self, "_t_start", None)
        if t0 is not None and (tracing.is_enabled()
                               or tracing.current_context() is not None):
            dur = time.time() - t0
            for op in self._ops:
                tracing.emit_span(f"data::{op.name}", t0, dur,
                                  {"depth": op.depth,
                                   **op.metrics.as_dict()})

    # --- consumption ---------------------------------------------------------

    def execute(self) -> Iterator[RefBundle]:
        """Yield the sink operator's bundles in source-block order."""
        self._start()
        sink = self._ops[-1]
        try:
            while True:
                while sink.output:
                    yield sink.output.popleft()
                if self.done():
                    break
                self._step()
        finally:
            self.shutdown()

    def execute_to_refs(self) -> List[Any]:
        """Drain fully; the materialize path."""
        return [b.block_ref for b in self.execute()]

    def execute_split(self, n: int) -> List[Iterator[RefBundle]]:
        """n shard iterators over ONE run — the sink must be an
        OutputSplitter(n). Each pull pumps the shared loop until that
        shard has a bundle; other shards' bundles wait in their queues."""
        from ray_tpu.data.execution.operators import OutputSplitter

        sink = self._ops[-1]
        if not isinstance(sink, OutputSplitter) or sink.n != n:
            raise ValueError("execute_split needs an OutputSplitter sink "
                             f"of width {n}")
        self._start()

        def _shard_iter(i: int) -> Iterator[RefBundle]:
            while True:
                if sink.shards[i]:
                    yield sink.shards[i].popleft()
                    continue
                if sink.shard_exhausted(i):
                    if self.done():
                        self.shutdown()
                    return
                self._step()

        return [_shard_iter(i) for i in range(n)]
