"""Concrete physical operators.

Reference map (python/ray/data/_internal/execution/operators/):
  InputDataBuffer            -> input_data_buffer.py (pre-existing refs
                                presented as an exhausted-source operator)
  TaskPoolMapOperator        -> task_pool_map_operator.py (one stateless
                                task per block; (block, meta) two-return
                                so the scheduler sees sizes without
                                fetching blocks)
  ActorPoolMapOperator       -> actor_pool_map_operator.py (stateful UDF
                                classes on a fixed pool; rides
                                util.ActorPool's ordered get_next)
  OutputSplitter             -> output_splitter.py (round-robin shard
                                queues for per-host train feeds)
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ray_tpu.data.execution.interfaces import (BlockMeta, OpBuffer,
                                               PhysicalOperator, RefBundle)


def _make_map_task(ops: List[tuple]):
    """Remote fn applying a slice of the logical op chain to one block;
    returns (block, meta) as TWO objects — the meta lands inline in the
    task reply (small), the block stays in the store."""
    import ray_tpu
    from ray_tpu.data.dataset import (_block_nbytes, _block_rows,
                                      _transform_block)

    @ray_tpu.remote
    def _map_block(block):
        out = _transform_block(block, ops)
        return out, {"nbytes": _block_nbytes(out), "rows": _block_rows(out)}

    return _map_block


class InputDataBuffer(PhysicalOperator):
    """Source operator: its output queue is the dataset's block refs.

    Byte sizes come from the owner-side object directory
    (Runtime.object_nbytes) — no fetch, no RPC; refs whose producing
    task hasn't finished report None and stay unknown until a
    downstream estimate covers them. Source bytes are NOT budgeted
    (the blocks exist regardless of scheduling)."""

    def __init__(self, block_refs: List[Any]):
        super().__init__("input", None)
        self._refs = list(block_refs)

    def start(self) -> None:
        from ray_tpu.core import runtime as rt

        r = rt.current_runtime_or_none()
        for i, ref in enumerate(self._refs):
            nbytes = r.object_nbytes(ref) if r is not None else None
            self.output.append(RefBundle(ref, BlockMeta(nbytes=nbytes), i))
            self.metrics.tasks_submitted += 1
            self.metrics.tasks_finished += 1
            self.metrics.bytes_out += nbytes or 0
        # source bundles are free to consume; keep the buffer's byte
        # counter out of budget math by reporting zero queued bytes
        self._refs = []

    def queued_output_bytes(self) -> int:
        return 0

    def completed(self) -> bool:
        return not self.output


class TaskPoolMapOperator(PhysicalOperator):
    """One stateless remote task per input block (ref:
    task_pool_map_operator.py). Tasks finish out of order; a reorder
    buffer releases bundles to `output` in input order so the sink's
    stream is bitwise-identical to the fused path's."""

    budgetable = True

    def __init__(self, name: str, ops: List[tuple],
                 input_op: PhysicalOperator, max_in_flight: int = 4):
        super().__init__(name, input_op, max_in_flight)
        self._task = _make_map_task(ops)
        self._in_flight: Dict[Any, Tuple[Any, int]] = {}  # meta_ref -> (block_ref, idx)
        self._order: Deque[int] = deque()                 # submission order
        self._reorder: Dict[int, RefBundle] = {}
        self._reorder_bytes = 0

    def num_in_flight(self) -> int:
        return len(self._in_flight)

    def submit_next(self) -> None:
        bundle = self.input_op.output.popleft()
        block_ref, meta_ref = self._task.options(num_returns=2).remote(
            bundle.block_ref)
        self._in_flight[meta_ref] = (block_ref, bundle.index)
        self._order.append(bundle.index)
        self.metrics.tasks_submitted += 1

    def poll(self) -> bool:
        import ray_tpu

        if not self._in_flight:
            return False
        refs = list(self._in_flight)
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        progressed = False
        for meta_ref in ready:
            block_ref, idx = self._in_flight.pop(meta_ref)
            meta = ray_tpu.get(meta_ref)   # raises the task's error, if any
            bundle = RefBundle(block_ref, BlockMeta(**meta), idx)
            self._reorder[idx] = bundle
            self._reorder_bytes += bundle.nbytes
            self.metrics.tasks_finished += 1
            self.metrics.rows_out += meta.get("rows") or 0
            self.metrics.bytes_out += meta.get("nbytes") or 0
            progressed = True
        while self._order and self._order[0] in self._reorder:
            idx = self._order.popleft()
            bundle = self._reorder.pop(idx)
            self._reorder_bytes -= bundle.nbytes
            self.output.append(bundle)
        return progressed

    def watch_refs(self) -> List[Any]:
        return list(self._in_flight)

    def _held_bundles(self) -> bool:
        return bool(self._reorder)

    def queued_output_bytes(self) -> int:
        return self.output.nbytes + self._reorder_bytes


class ActorPoolMapOperator(PhysicalOperator):
    """Stateful-UDF map over a fixed actor pool (ref:
    actor_pool_map_operator.py). The UDF class constructs once per actor;
    blocks travel as refs straight into the actors. Dispatch and harvest
    ride util.ActorPool: results come back via the ordered get_next
    (submission order == input order), so no reorder buffer is needed."""

    budgetable = True

    def __init__(self, name: str, fn_cls: type, ctor_args: tuple,
                 pool_size: int, num_cpus_per_actor: float,
                 batch_size: Optional[int],
                 fused_ops: List[tuple],
                 input_op: PhysicalOperator,
                 max_in_flight: Optional[int] = None):
        super().__init__(name, input_op, max_in_flight or pool_size)
        self._fn_cls = fn_cls
        self._ctor_args = tuple(ctor_args)
        self._pool_size = pool_size
        self._num_cpus = num_cpus_per_actor
        self._batch_size = batch_size
        self._fused_ops = fused_ops
        self._pool = None
        self._actors: List[Any] = []
        self._pending_out: Deque[Tuple[Any, int]] = deque()  # (block_ref, idx)
        self._submitted = 0
        self._finished = 0

    def start(self) -> None:
        import ray_tpu
        from ray_tpu.util.actor_pool import ActorPool

        fused = self._fused_ops

        @ray_tpu.remote
        class _MapWorker:
            def __init__(self, cls, args):
                self.fn = cls(*args)

            @ray_tpu.method(num_returns=2)
            def apply(self, block, bs):
                from ray_tpu.data.dataset import (_apply_rebatched,
                                                  _block_nbytes, _block_rows,
                                                  _transform_block)

                block = _transform_block(block, fused)
                out = _apply_rebatched(self.fn, block, bs)
                return out, {"nbytes": _block_nbytes(out),
                             "rows": _block_rows(out)}

        self._actors = [
            _MapWorker.options(num_cpus=self._num_cpus).remote(
                self._fn_cls, self._ctor_args)
            for _ in range(self._pool_size)]
        self._pool = ActorPool(self._actors)

    def num_in_flight(self) -> int:
        return self._submitted - self._finished

    def submit_next(self) -> None:
        bundle = self.input_op.output.popleft()
        bs = self._batch_size
        pending_out = self._pending_out
        idx = bundle.index

        def _dispatch(actor, block_ref):
            block_ref_out, meta_ref = actor.apply.remote(block_ref, bs)
            # ActorPool dispatches FIFO, so appending here keeps
            # pending_out aligned with the ordered get_next stream
            pending_out.append((block_ref_out, idx))
            return meta_ref

        self._pool.submit(_dispatch, bundle.block_ref)
        self._submitted += 1
        self.metrics.tasks_submitted += 1

    def poll(self) -> bool:
        progressed = False
        while self._pool is not None and self._pool.has_next():
            try:
                meta = self._pool.get_next(timeout=0)
            except TimeoutError:
                break
            block_ref, idx = self._pending_out.popleft()
            bundle = RefBundle(block_ref, BlockMeta(**meta), idx)
            self.output.append(bundle)
            self._finished += 1
            self.metrics.tasks_finished += 1
            self.metrics.rows_out += meta.get("rows") or 0
            self.metrics.bytes_out += meta.get("nbytes") or 0
            progressed = True
        return progressed

    def watch_refs(self) -> List[Any]:
        if self._pool is None:
            return []
        return list(self._pool._future_to_actor)

    def shutdown(self) -> None:
        import ray_tpu

        actors, self._actors, self._pool = self._actors, [], None
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class OutputSplitter(PhysicalOperator):
    """Round-robin fan-out into n shard queues (ref: output_splitter.py
    — the operator behind streaming_split train ingest). Shard queues
    are exempt from the byte budget: shard i may only fill because its
    consumer lags the others, and throttling upstream then would starve
    the shards that ARE consuming (the reference makes the same
    coordinated-consumers assumption)."""

    def __init__(self, input_op: PhysicalOperator, n: int):
        super().__init__("split", input_op)
        self.n = n
        self.shards: List[OpBuffer] = [OpBuffer() for _ in range(n)]
        self._rr = 0

    def poll(self) -> bool:
        progressed = False
        while self.input_op.output:
            bundle = self.input_op.output.popleft()
            self.shards[self._rr % self.n].append(bundle)
            self._rr += 1
            self.metrics.rows_out += bundle.meta.rows or 0
            self.metrics.bytes_out += bundle.nbytes
            progressed = True
        return progressed

    def queued_output_bytes(self) -> int:
        return 0

    def shard_exhausted(self, i: int) -> bool:
        return self.inputs_done() and not self.shards[i]

    def completed(self) -> bool:
        return self.inputs_done() and not any(self.shards)
