"""Physical-operator interfaces for the streaming executor.

Reference map (python/ray/data/_internal/execution/):
  RefBundle                  -> interfaces/ref_bundle.py (block ref + metadata
                                travelling together so the scheduler can do
                                byte accounting without fetching blocks)
  OpBufferQueue              -> OpBuffer (FIFO of bundles with byte totals)
  PhysicalOperator           -> interfaces/physical_operator.py (the
                                submit/poll/completed contract the
                                StreamingExecutor drives)
  OpRuntimeMetrics           -> OpMetrics

Blocks never flow through the executor — only refs + BlockMeta do. The
driver process fetches a block exactly once, when the consumer pulls it
from the sink operator.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

_memory_mod = None


def _memattr():
    """Lazy memory-attribution tracker (observability imports core at
    module top, so execution modules must import it on first use)."""
    global _memory_mod
    if _memory_mod is None:
        from ray_tpu.observability import memory
        _memory_mod = memory.tracker()
    return _memory_mod


@dataclass
class BlockMeta:
    """Size/shape facts about a block, carried beside its ref.

    nbytes is None when the producer hasn't reported yet (e.g. a source
    ref whose read task is still running) — the ResourceManager then
    falls back to its running per-operator output estimate."""

    nbytes: Optional[int] = None
    rows: Optional[int] = None


@dataclass
class RefBundle:
    """One block ref + metadata + its position in the original block
    order (map operators are 1:1, so the index survives the whole
    chain and the sink can restore source order bitwise)."""

    block_ref: Any
    meta: BlockMeta
    index: int

    @property
    def nbytes(self) -> int:
        return self.meta.nbytes or 0


class OpBuffer:
    """FIFO queue of RefBundles with byte accounting (ref:
    OpBufferQueue — the unit select_operator_to_run budgets against)."""

    def __init__(self) -> None:
        self._q: Deque[RefBundle] = deque()
        self._nbytes = 0

    def append(self, bundle: RefBundle) -> None:
        self._q.append(bundle)
        self._nbytes += bundle.nbytes
        # Queued blocks belong to the data plane: retag the (possibly
        # worker-produced) block so memory_report() attributes it to
        # "data" instead of the generic "user" bucket.
        oid = getattr(bundle.block_ref, "id", None)
        if oid is not None:
            _memattr().retag(oid, "data")

    def popleft(self) -> RefBundle:
        bundle = self._q.popleft()
        self._nbytes -= bundle.nbytes
        oid = getattr(bundle.block_ref, "id", None)
        if oid is not None:
            _memattr().touch(oid)   # consumption is an access
        return bundle

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def __len__(self) -> int:
        return len(self._q)

    def __bool__(self) -> bool:
        return bool(self._q)


@dataclass
class OpMetrics:
    """Per-operator counters (ref: OpRuntimeMetrics). backpressure_s
    accumulates wall time the operator spent input-ready but blocked by
    the ResourceManager's output-queue budget."""

    tasks_submitted: int = 0
    tasks_finished: int = 0
    rows_out: int = 0
    bytes_out: int = 0
    backpressure_s: float = 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {"tasks_submitted": self.tasks_submitted,
                "tasks_finished": self.tasks_finished,
                "rows_out": self.rows_out,
                "bytes_out": self.bytes_out,
                "backpressure_s": round(self.backpressure_s, 4)}


class PhysicalOperator:
    """Base contract the StreamingExecutor schedules against.

    Operators form a linear chain: each pops input bundles directly from
    `input_op.output`, so "queued output bytes" of an operator is exactly
    the bytes it produced that no downstream task has consumed yet."""

    #: operators whose output queues count against the byte budget
    budgetable: bool = False

    def __init__(self, name: str,
                 input_op: Optional["PhysicalOperator"],
                 max_in_flight: int = 4):
        self.name = name
        self.input_op = input_op
        self.output = OpBuffer()
        self.metrics = OpMetrics()
        self.max_in_flight = max_in_flight
        self.depth = 0 if input_op is None else input_op.depth + 1

    # --- scheduling interface ------------------------------------------------

    def start(self) -> None:
        """Acquire resources (actor pools, input metadata)."""

    def has_input(self) -> bool:
        return self.input_op is not None and bool(self.input_op.output)

    def num_in_flight(self) -> int:
        return 0

    def can_submit(self) -> bool:
        """Input available and a task slot free — budget NOT considered
        here; that's the ResourceManager's call."""
        return self.has_input() and self.num_in_flight() < self.max_in_flight

    def submit_next(self) -> None:
        raise NotImplementedError

    def poll(self) -> bool:
        """Harvest finished tasks into `output`; True if anything moved."""
        return False

    def watch_refs(self) -> List[Any]:
        """Refs the executor may block on when nothing else progresses."""
        return []

    def inputs_done(self) -> bool:
        return self.input_op is None or self.input_op.completed()

    def completed(self) -> bool:
        return (self.inputs_done() and self.num_in_flight() == 0
                and not self.output and not self._held_bundles())

    def _held_bundles(self) -> bool:
        """Bundles finished but not yet in `output` (reorder buffers)."""
        return False

    def queued_output_bytes(self) -> int:
        """Unconsumed output bytes this operator is responsible for."""
        return self.output.nbytes

    def shutdown(self) -> None:
        """Release resources; idempotent."""

    def __repr__(self) -> str:
        return (f"{type(self).__name__}({self.name!r}, depth={self.depth}, "
                f"in_flight={self.num_in_flight()}, "
                f"queued={len(self.output)})")
