"""CompiledChainMapOperator: the whole logical op chain as ONE physical
operator running over standing channels (ray_tpu.dag.compiled).

The task-pool path pays a full submit round per block per operator:
build a task spec, lease a worker, ship the spec, watch the reply. For
a FIXED chain of pure map ops none of that per-call work carries
information — the chain is the same every block. Under the "compiled"
execution policy, build_pipeline fuses the chain into this operator: a
small pool of `_ChainWorker` actors, each fronted by a compiled
`InputNode -> worker.apply` graph whose channel was negotiated once at
start(). Per block, submit_next() is one oneway frame enqueue
(CompiledDAG.execute), and results stream back on the standing result
edge — no task specs, no scheduler round, no reply round-trips.

Data plane: the block REF rides the input frame (refs pickle to
borrows); the worker fetches, transforms, and returns the transformed
block inline on the result frame. The driver re-put()s it so the
resulting bundle ref is DRIVER-owned and survives pool teardown — the
pool actors die with the run, materialized blocks must not.

In-flight work here is CompiledDAGRefs, not ObjectRefs, so watch_refs()
is empty; the StreamingExecutor's idle branch covers that case by
napping briefly when any operator reports untracked in-flight work.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Tuple

from ray_tpu.data.execution.interfaces import (BlockMeta, PhysicalOperator,
                                               RefBundle)


class CompiledChainMapOperator(PhysicalOperator):
    """Fused map chain over a compiled-graph actor pool.

    Completion order is per-actor FIFO (channel sequence numbers) but
    round-robin across the pool interleaves, so a reorder buffer
    restores source-block order exactly like TaskPoolMapOperator."""

    budgetable = True

    def __init__(self, name: str, ops: List[tuple],
                 input_op: PhysicalOperator, pool_size: int = 2,
                 max_in_flight: int = 4,
                 num_cpus_per_actor: float = 0.25):
        super().__init__(name, input_op, max_in_flight)
        self._ops = list(ops)
        self._pool_size = max(1, pool_size)
        # fractional so the pool lane-packs instead of demanding a whole
        # core per actor (same reasoning as ActorPoolStrategy's 0.5)
        self._num_cpus = num_cpus_per_actor
        self._dags: List[Any] = []
        self._rr = 0
        self._pending: Deque[Tuple[Any, int]] = deque()  # (ref, idx)
        self._order: Deque[int] = deque()
        self._reorder: Dict[int, RefBundle] = {}
        self._reorder_bytes = 0

    def start(self) -> None:
        import ray_tpu
        from ray_tpu.dag import InputNode

        # ops ride the class closure (cloudpickle), same as
        # ActorPoolMapOperator's _MapWorker — user lambdas don't survive
        # the plain-pickle ctor-arg path
        chain_ops = self._ops

        @ray_tpu.remote
        class _ChainWorker:
            def apply(self, block_ref):
                import ray_tpu
                from ray_tpu.data.dataset import (_block_nbytes, _block_rows,
                                                  _transform_block)

                block = ray_tpu.get(block_ref)
                out = _transform_block(block, chain_ops)
                return {"block": out, "nbytes": _block_nbytes(out),
                        "rows": _block_rows(out)}

        cls = _ChainWorker.options(num_cpus=self._num_cpus)
        for _ in range(self._pool_size):
            with InputNode() as inp:
                leaf = cls.bind().apply.bind(inp)
            self._dags.append(leaf.experimental_compile())

    def num_in_flight(self) -> int:
        return len(self._pending)

    def submit_next(self) -> None:
        bundle = self.input_op.output.popleft()
        dag = self._dags[self._rr % len(self._dags)]
        self._rr += 1
        ref = dag.execute(bundle.block_ref)
        self._pending.append((ref, bundle.index))
        self._order.append(bundle.index)
        self.metrics.tasks_submitted += 1

    def poll(self) -> bool:
        import ray_tpu

        progressed = False
        still: Deque[Tuple[Any, int]] = deque()
        while self._pending:
            ref, idx = self._pending.popleft()
            if not ref.done():
                still.append((ref, idx))
                continue
            res = ref.get(timeout=30.0)  # raises the chain's error, if any
            out_ref = ray_tpu.put(res["block"])
            meta = {"nbytes": res["nbytes"], "rows": res["rows"]}
            bundle = RefBundle(out_ref, BlockMeta(**meta), idx)
            self._reorder[idx] = bundle
            self._reorder_bytes += bundle.nbytes
            self.metrics.tasks_finished += 1
            self.metrics.rows_out += meta.get("rows") or 0
            self.metrics.bytes_out += meta.get("nbytes") or 0
            progressed = True
        self._pending = still
        while self._order and self._order[0] in self._reorder:
            idx = self._order.popleft()
            bundle = self._reorder.pop(idx)
            self._reorder_bytes -= bundle.nbytes
            self.output.append(bundle)
        return progressed

    def _held_bundles(self) -> bool:
        return bool(self._reorder)

    def queued_output_bytes(self) -> int:
        return self.output.nbytes + self._reorder_bytes

    def shutdown(self) -> None:
        dags, self._dags = self._dags, []
        for dag in dags:
            try:
                dag.teardown()
            except Exception:
                pass
