"""ResourceManager: budget accounting + the select_operator_to_run policy.

Reference map (python/ray/data/_internal/execution/):
  resource_manager.py        -> per-operator output-queue budgets derived
                                from the object store size
  streaming_executor_state.py:376 select_operator_to_run
                             -> pick the operator whose output queue is
                                under budget, preferring the operator
                                with the least unconsumed output (i.e.
                                the downstream-starved one), so a slow
                                consumer rate-limits its producers and a
                                drained pipeline refills from the top.

Liveness guarantee: an operator with an empty output queue and no task
in flight is ALWAYS budget-eligible (one task may exceed a tiny budget —
it still runs), and when every candidate is budget-blocked but nothing
is in flight anywhere, the most downstream candidate runs anyway.
Together these make "all queues empty => schedulable" unconditional, so
the executor cannot deadlock on budgets alone.
"""

from __future__ import annotations

import time
from typing import List, Optional

from ray_tpu.data.execution.interfaces import PhysicalOperator, _memattr

#: fallback per-task output estimate before any sizes are known
_DEFAULT_OUTPUT_EST = 1 << 20


def derive_budget_bytes(fraction: Optional[float] = None) -> int:
    """Total unconsumed-output budget from the runtime's object store
    size (Config.data_execution_budget_fraction unless overridden)."""
    from ray_tpu.core import runtime as rt

    r = rt.current_runtime_or_none()
    if r is not None:
        frac = (fraction if fraction is not None
                else r.cfg.data_execution_budget_fraction)
        return max(1, int(r.cfg.object_store_memory * frac))
    return max(1, int((2 << 30) * (fraction if fraction is not None
                                   else 0.25)))


class ResourceManager:
    """Tracks per-operator in-flight slots and queued output bytes
    against a byte budget; owns the scheduling policy."""

    def __init__(self, ops: List[PhysicalOperator],
                 total_budget_bytes: Optional[int] = None,
                 per_op_budget_bytes: Optional[int] = None):
        self._ops = ops
        budgeted = [op for op in ops if op.budgetable]
        if per_op_budget_bytes is not None:
            self.per_op_budget = max(1, int(per_op_budget_bytes))
        else:
            total = (total_budget_bytes if total_budget_bytes is not None
                     else derive_budget_bytes())
            self.per_op_budget = max(1, total // max(1, len(budgeted)))
        self._last_select_t: Optional[float] = None

    # --- accounting ----------------------------------------------------------

    def est_output_bytes(self, op: PhysicalOperator) -> int:
        """Expected bytes ONE more task of `op` will add to its output
        queue: running average of finished outputs, else the size of the
        input bundle it would consume, else a 1 MiB prior."""
        m = op.metrics
        if m.tasks_finished:
            return max(1, m.bytes_out // m.tasks_finished)
        if op.input_op is not None and op.input_op.output:
            q = op.input_op.output
            if q.nbytes:
                return max(1, q.nbytes // len(q))
        return _DEFAULT_OUTPUT_EST

    def outqueue_usage(self, op: PhysicalOperator) -> int:
        """Actual queued output bytes plus the projected output of every
        in-flight task — admission must see bytes BEFORE they land, or a
        burst of submissions overshoots the budget by a whole window."""
        return (op.queued_output_bytes()
                + op.num_in_flight() * self.est_output_bytes(op))

    def under_budget(self, op: PhysicalOperator) -> bool:
        if not op.budgetable:
            return True
        if op.queued_output_bytes() == 0 and op.num_in_flight() == 0:
            return True   # liveness: empty operators always admit one task
        return (self.outqueue_usage(op) + self.est_output_bytes(op)
                <= self.per_op_budget)

    def _track_queued(self, ops: List[PhysicalOperator]) -> None:
        """Mirror the pipeline's total unconsumed output bytes into the
        memory plane (synthetic aggregate; per-block records are retagged
        "data" by OpBuffer.append). Runs once per scheduling decision."""
        total = sum(op.queued_output_bytes() for op in ops)
        mem = _memattr()
        key = "data:outqueues"
        if total > 0:
            mem.attribute(key, "data", total, store=False,
                          budget=self.per_op_budget)
            self._tracked = True
        elif getattr(self, "_tracked", False):
            mem.release(key)
            self._tracked = False

    # --- policy --------------------------------------------------------------

    def select_operator_to_run(
            self, ops: Optional[List[PhysicalOperator]] = None
    ) -> Optional[PhysicalOperator]:
        """One scheduling decision (ref: select_operator_to_run). Returns
        the operator to hand a task, or None when nothing should run."""
        ops = ops if ops is not None else self._ops
        now = time.monotonic()
        dt = (now - self._last_select_t) if self._last_select_t else 0.0
        self._last_select_t = now
        self._track_queued(ops)

        candidates = [op for op in ops if op.can_submit()]
        eligible = []
        for op in candidates:
            if self.under_budget(op):
                eligible.append(op)
            else:
                op.metrics.backpressure_s += dt
        if not eligible:
            if candidates and not any(op.num_in_flight() for op in ops):
                # budget-blocked but the pipeline is idle: force the most
                # downstream candidate so progress is unconditional
                return max(candidates, key=lambda op: op.depth)
            return None
        # least unconsumed output first (drain towards the consumer);
        # among ties prefer the most downstream operator
        return min(eligible,
                   key=lambda op: (self.outqueue_usage(op), -op.depth))
