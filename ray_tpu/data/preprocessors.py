"""Preprocessors: fit on a Dataset, transform Datasets/batches.

Reference: python/ray/data/preprocessors/ — Preprocessor base
(fit/transform/transform_batch), scalers (scaler.py), encoders
(encoder.py), imputer, concatenator, chain. Stats are computed with the
dataset's distributed aggregates; transform is a map_batches.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return ds.map_batches(self.transform_batch)

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds):
        pass

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (ref: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str], ddof: int = 0):
        self.columns = columns
        self.ddof = ddof
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            mean = ds.mean(c)
            std = ds.std(c, ddof=self.ddof) or 0.0
            self.stats_[c] = (mean, std if std > 0 else 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = (np.asarray(batch[c], dtype=np.float64) - mean) / std
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            lo, hi = ds.min(c), ds.max(c)
            self.stats_[c] = (lo, (hi - lo) or 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, span = self.stats_[c]
            out[c] = (np.asarray(batch[c], dtype=np.float64) - lo) / span
        return out


def _distributed_unique(ds, column: str) -> np.ndarray:
    """Per-block np.unique in remote tasks; only the (small) unique sets
    reach the driver."""
    uniq: set = set()
    per_block = ds.select_columns([column]).map_batches(
        lambda b: {column: np.unique(np.asarray(b[column]))})
    for block in per_block._iter_blocks():
        uniq.update(np.asarray(block[column]).tolist())
    return np.asarray(sorted(uniq))


class LabelEncoder(Preprocessor):
    """Categorical → ordinal int (ref: preprocessors/encoder.py)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[np.ndarray] = None

    def _fit(self, ds):
        self.classes_ = _distributed_unique(ds, self.label_column)

    def transform_batch(self, batch):
        out = dict(batch)
        lut = {v: i for i, v in enumerate(self.classes_.tolist())}
        out[self.label_column] = np.asarray(
            [lut[v] for v in np.asarray(batch[self.label_column]).tolist()])
        return out


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.classes_: Dict[str, np.ndarray] = {}

    def _fit(self, ds):
        for c in self.columns:
            self.classes_[c] = _distributed_unique(ds, c)

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        for c in self.columns:
            vals = np.asarray(batch[c])
            for cls in self.classes_[c].tolist():
                out[f"{c}_{cls}"] = (vals == cls).astype(np.int64)
        return out


class SimpleImputer(Preprocessor):
    """Fill NaNs with mean ('mean') or a constant ('constant')."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Any = 0.0):
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, float] = {}

    def _needs_fit(self):
        return self.strategy == "mean"

    def _fit(self, ds):
        if self.strategy != "mean":
            return
        for c in self.columns:
            # NaN-aware mean over blocks
            def _clean(b, c=c):
                col = np.asarray(b[c], dtype=np.float64)
                return {c: col[~np.isnan(col)]}

            self.stats_[c] = ds.select_columns([c]).map_batches(_clean).mean(c)

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            col = np.asarray(batch[c], dtype=np.float64)
            fill = self.stats_.get(c, self.fill_value)
            out[c] = np.where(np.isnan(col), fill, col)
        return out


class Concatenator(Preprocessor):
    """Merge feature columns into one float matrix column (ref:
    preprocessors/concatenator.py) — the standard last step before
    feeding a jax model."""

    def __init__(self, columns: List[str], output_column_name: str = "features",
                 dtype=np.float32):
        self.columns = columns
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _needs_fit(self):
        return False

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        mats = [np.asarray(batch[c], dtype=self.dtype).reshape(
            len(np.asarray(batch[c])), -1) for c in self.columns]
        out[self.output_column_name] = np.concatenate(mats, axis=1)
        return out


class Chain(Preprocessor):
    def __init__(self, *steps: Preprocessor):
        self.steps = steps

    def fit(self, ds):
        for i, step in enumerate(self.steps):
            step.fit(ds)
            if i < len(self.steps) - 1:
                ds = step.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for step in self.steps:
            ds = step.transform(ds)
        return ds

    def transform_batch(self, batch):
        for step in self.steps:
            batch = step.transform_batch(batch)
        return batch


class BatchMapper(Preprocessor):
    """Apply a user batch function, no fitting (ref:
    preprocessors/batch_mapper.py)."""

    def __init__(self, fn, batch_format: Optional[str] = None):
        self.fn = fn
        self.batch_format = batch_format

    def _needs_fit(self) -> bool:
        return False

    def transform(self, ds):
        return ds.map_batches(self.fn, batch_format=self.batch_format)

    def transform_batch(self, batch):
        # honor batch_format on the direct-batch path too (Chain calls
        # transform_batch; the fn may be written against a DataFrame)
        from ray_tpu.data.dataset import _coerce_block, _to_batch_format

        return _coerce_block(self.fn(_to_batch_format(batch,
                                                      self.batch_format)))


class Normalizer(Preprocessor):
    """Row-wise norm scaling across columns (ref:
    preprocessors/normalizer.py; norms l1/l2/max)."""

    def __init__(self, columns: List[str], norm: str = "l2"):
        if norm not in ("l1", "l2", "max"):
            raise ValueError(f"unsupported norm {norm!r}")
        self.columns = columns
        self.norm = norm

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch):
        cols = [np.asarray(batch[c], np.float64) for c in self.columns]
        mat = np.stack(cols, axis=1)
        if self.norm == "l1":
            denom = np.abs(mat).sum(axis=1)
        elif self.norm == "l2":
            denom = np.sqrt((mat * mat).sum(axis=1))
        else:
            denom = np.abs(mat).max(axis=1)
        denom = np.where(denom == 0, 1.0, denom)
        out = dict(batch)
        for i, c in enumerate(self.columns):
            out[c] = mat[:, i] / denom
        return out


class MaxAbsScaler(Preprocessor):
    """x / max|x| per column (ref: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, float] = {}

    def _fit(self, ds):
        for c in self.columns:
            m = max(abs(ds.min(c)), abs(ds.max(c)))
            self.stats_[c] = m or 1.0

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = np.asarray(batch[c], np.float64) / self.stats_[c]
        return out


class RobustScaler(Preprocessor):
    """(x - median) / IQR per column (ref: preprocessors/scaler.py).

    Exact quantiles need the whole column: blocks stream to the driver
    one at a time (only the selected column), so the driver holds one
    column, not the dataset — fine for numeric columns, the same
    trade-off the reference's exact-quantile path makes."""

    def __init__(self, columns: List[str],
                 quantile_range: tuple = (0.25, 0.75)):
        self.columns = columns
        self.quantile_range = quantile_range
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        lo_q, hi_q = self.quantile_range
        for c in self.columns:
            parts = [np.asarray(b[c], np.float64)
                     for b in ds.select_columns([c])._iter_blocks()
                     if len(b[c])]
            if not parts:
                self.stats_[c] = (0.0, 1.0)
                continue
            vals = np.concatenate(parts)
            med = float(np.quantile(vals, 0.5))
            iqr = float(np.quantile(vals, hi_q) - np.quantile(vals, lo_q))
            self.stats_[c] = (med, iqr or 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            med, iqr = self.stats_[c]
            out[c] = (np.asarray(batch[c], np.float64) - med) / iqr
        return out


class PowerTransformer(Preprocessor):
    """Box-Cox / Yeo-Johnson with a CALLER-CHOSEN lambda (ref:
    preprocessors/transformer.py — the reference likewise takes `power`
    as a parameter rather than estimating it)."""

    def __init__(self, columns: List[str], power: float,
                 method: str = "yeo-johnson"):
        if method not in ("yeo-johnson", "box-cox"):
            raise ValueError(f"unsupported method {method!r}")
        self.columns = columns
        self.power = power
        self.method = method

    def _needs_fit(self) -> bool:
        return False

    def _apply(self, x: np.ndarray) -> np.ndarray:
        lam = self.power
        if self.method == "box-cox":
            if np.any(x <= 0):
                # silent NaN/-inf would flow into training; sklearn's
                # box-cox raises on non-positive data for the same reason
                raise ValueError(
                    "box-cox requires strictly positive values; use "
                    "method='yeo-johnson' for zero/negative data")
            return np.log(x) if lam == 0 else (x ** lam - 1) / lam
        pos = x >= 0
        out = np.empty_like(x, dtype=np.float64)
        if lam == 0:
            out[pos] = np.log1p(x[pos])
        else:
            out[pos] = ((x[pos] + 1) ** lam - 1) / lam
        if lam == 2:
            out[~pos] = -np.log1p(-x[~pos])
        else:
            out[~pos] = -((-x[~pos] + 1) ** (2 - lam) - 1) / (2 - lam)
        return out

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = self._apply(np.asarray(batch[c], np.float64))
        return out


class UniformKBinsDiscretizer(Preprocessor):
    """Equal-width binning into int bin ids (ref:
    preprocessors/discretizer.py)."""

    def __init__(self, columns: List[str], bins: int):
        self.columns = columns
        self.bins = bins
        self.stats_: Dict[str, np.ndarray] = {}

    def _fit(self, ds):
        for c in self.columns:
            lo, hi = ds.min(c), ds.max(c)
            self.stats_[c] = np.linspace(lo, hi, self.bins + 1)[1:-1]

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = np.digitize(np.asarray(batch[c], np.float64),
                                 self.stats_[c]).astype(np.int64)
        return out


class CustomKBinsDiscretizer(Preprocessor):
    """Binning on caller-provided edges (ref: discretizer.py)."""

    def __init__(self, columns: List[str], bins: List[float]):
        self.columns = columns
        self.bins = np.asarray(bins, np.float64)

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = (np.digitize(np.asarray(batch[c], np.float64),
                                  self.bins) - 1).astype(np.int64)
        return out


class OrdinalEncoder(Preprocessor):
    """Categorical -> ordinal ints per column, like LabelEncoder over
    many columns (ref: preprocessors/encoder.py OrdinalEncoder)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Dict[Any, int]] = {}

    def _fit(self, ds):
        for c in self.columns:
            cats = _distributed_unique(ds, c)
            self.stats_[c] = {v: i for i, v in enumerate(cats.tolist())}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            m = self.stats_[c]
            out[c] = np.asarray([m.get(v, -1)
                                 for v in np.asarray(batch[c]).tolist()],
                                np.int64)
        return out


class MultiHotEncoder(Preprocessor):
    """List-valued categorical column -> multi-hot vector (ref:
    preprocessors/encoder.py MultiHotEncoder)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Dict[Any, int]] = {}

    def _fit(self, ds):
        for c in self.columns:
            # per-block unique in remote tasks; only small unique sets
            # reach the driver (same pattern as _distributed_unique)
            uniq: set = set()
            reduced = ds.select_columns([c]).map_batches(
                lambda b, col=c: {col: np.asarray(
                    sorted({v for row in np.asarray(b[col], dtype=object)
                            for v in list(row)}), dtype=object)})
            for block in reduced._iter_blocks():
                uniq.update(np.asarray(block[c]).tolist())
            self.stats_[c] = {v: i for i, v in enumerate(sorted(uniq))}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            m = self.stats_[c]
            rows = np.asarray(batch[c], dtype=object)
            enc = np.zeros((len(rows), len(m)), np.int64)
            for i, row in enumerate(rows):
                for v in list(row):
                    j = m.get(v)
                    if j is not None:
                        enc[i, j] = 1
            out[c] = enc
        return out


class FeatureHasher(Preprocessor):
    """Token-count dict -> fixed-width hashed feature vector (ref:
    preprocessors/hasher.py)."""

    def __init__(self, columns: List[str], num_features: int,
                 output_column: str = "hashed_features"):
        self.columns = columns
        self.num_features = num_features
        self.output_column = output_column

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch):
        import zlib

        n = len(next(iter(batch.values())))
        mat = np.zeros((n, self.num_features), np.float64)
        for c in self.columns:
            col = np.asarray(batch[c], dtype=object)
            for i in range(n):
                # stable across processes (builtin hash() is salted)
                j = zlib.crc32(f"{c}={col[i]}".encode()) \
                    % self.num_features
                mat[i, j] += 1.0
        out = {k: v for k, v in batch.items() if k not in self.columns}
        out[self.output_column] = mat
        return out


class Tokenizer(Preprocessor):
    """String column -> list of tokens (ref: preprocessors/tokenizer.py;
    default splits on whitespace)."""

    def __init__(self, columns: List[str], tokenization_fn=None):
        self.columns = columns
        self.fn = tokenization_fn or (lambda s: s.split())

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = np.asarray(
                [self.fn(str(v)) for v in np.asarray(batch[c])],
                dtype=object)
        return out


class CountVectorizer(Preprocessor):
    """Token counts over a fitted vocabulary (ref:
    preprocessors/vectorizer.py)."""

    def __init__(self, columns: List[str], max_features: Optional[int] = None,
                 tokenization_fn=None):
        self.columns = columns
        self.max_features = max_features
        self.fn = tokenization_fn or (lambda s: s.split())
        self.stats_: Dict[str, Dict[str, int]] = {}

    def _fit(self, ds):
        from collections import Counter

        fn = self.fn
        for c in self.columns:
            # tokenize + count per block remotely; only the (small)
            # token->count dicts travel to the driver for the merge
            def _count(b, col=c):
                cnt: Counter = Counter()
                for v in np.asarray(b[col]):
                    cnt.update(fn(str(v)))
                return {"counts": np.asarray([dict(cnt)], dtype=object)}

            counts: Counter = Counter()
            for block in ds.select_columns([c]).map_batches(
                    _count)._iter_blocks():
                for d in np.asarray(block["counts"], dtype=object):
                    counts.update(d)
            vocab = [t for t, _ in counts.most_common(self.max_features)]
            self.stats_[c] = {t: i for i, t in enumerate(sorted(vocab))}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            vocab = self.stats_[c]
            rows = np.asarray(batch[c])
            mat = np.zeros((len(rows), len(vocab)), np.int64)
            for i, v in enumerate(rows):
                for t in self.fn(str(v)):
                    j = vocab.get(t)
                    if j is not None:
                        mat[i, j] += 1
            out[c] = mat
        return out
