"""Preprocessors: fit on a Dataset, transform Datasets/batches.

Reference: python/ray/data/preprocessors/ — Preprocessor base
(fit/transform/transform_batch), scalers (scaler.py), encoders
(encoder.py), imputer, concatenator, chain. Stats are computed with the
dataset's distributed aggregates; transform is a map_batches.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return ds.map_batches(self.transform_batch)

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds):
        pass

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (ref: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str], ddof: int = 0):
        self.columns = columns
        self.ddof = ddof
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            mean = ds.mean(c)
            std = ds.std(c, ddof=self.ddof) or 0.0
            self.stats_[c] = (mean, std if std > 0 else 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = (np.asarray(batch[c], dtype=np.float64) - mean) / std
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            lo, hi = ds.min(c), ds.max(c)
            self.stats_[c] = (lo, (hi - lo) or 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, span = self.stats_[c]
            out[c] = (np.asarray(batch[c], dtype=np.float64) - lo) / span
        return out


def _distributed_unique(ds, column: str) -> np.ndarray:
    """Per-block np.unique in remote tasks; only the (small) unique sets
    reach the driver."""
    uniq: set = set()
    per_block = ds.select_columns([column]).map_batches(
        lambda b: {column: np.unique(np.asarray(b[column]))})
    for block in per_block._iter_blocks():
        uniq.update(np.asarray(block[column]).tolist())
    return np.asarray(sorted(uniq))


class LabelEncoder(Preprocessor):
    """Categorical → ordinal int (ref: preprocessors/encoder.py)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[np.ndarray] = None

    def _fit(self, ds):
        self.classes_ = _distributed_unique(ds, self.label_column)

    def transform_batch(self, batch):
        out = dict(batch)
        lut = {v: i for i, v in enumerate(self.classes_.tolist())}
        out[self.label_column] = np.asarray(
            [lut[v] for v in np.asarray(batch[self.label_column]).tolist()])
        return out


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.classes_: Dict[str, np.ndarray] = {}

    def _fit(self, ds):
        for c in self.columns:
            self.classes_[c] = _distributed_unique(ds, c)

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        for c in self.columns:
            vals = np.asarray(batch[c])
            for cls in self.classes_[c].tolist():
                out[f"{c}_{cls}"] = (vals == cls).astype(np.int64)
        return out


class SimpleImputer(Preprocessor):
    """Fill NaNs with mean ('mean') or a constant ('constant')."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Any = 0.0):
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, float] = {}

    def _needs_fit(self):
        return self.strategy == "mean"

    def _fit(self, ds):
        if self.strategy != "mean":
            return
        for c in self.columns:
            # NaN-aware mean over blocks
            def _clean(b, c=c):
                col = np.asarray(b[c], dtype=np.float64)
                return {c: col[~np.isnan(col)]}

            self.stats_[c] = ds.select_columns([c]).map_batches(_clean).mean(c)

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            col = np.asarray(batch[c], dtype=np.float64)
            fill = self.stats_.get(c, self.fill_value)
            out[c] = np.where(np.isnan(col), fill, col)
        return out


class Concatenator(Preprocessor):
    """Merge feature columns into one float matrix column (ref:
    preprocessors/concatenator.py) — the standard last step before
    feeding a jax model."""

    def __init__(self, columns: List[str], output_column_name: str = "features",
                 dtype=np.float32):
        self.columns = columns
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _needs_fit(self):
        return False

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        mats = [np.asarray(batch[c], dtype=self.dtype).reshape(
            len(np.asarray(batch[c])), -1) for c in self.columns]
        out[self.output_column_name] = np.concatenate(mats, axis=1)
        return out


class Chain(Preprocessor):
    def __init__(self, *steps: Preprocessor):
        self.steps = steps

    def fit(self, ds):
        for i, step in enumerate(self.steps):
            step.fit(ds)
            if i < len(self.steps) - 1:
                ds = step.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for step in self.steps:
            ds = step.transform(ds)
        return ds

    def transform_batch(self, batch):
        for step in self.steps:
            batch = step.transform_batch(batch)
        return batch
