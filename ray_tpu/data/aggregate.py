"""Aggregation functions for Dataset.groupby / global aggregates.

Reference: python/ray/data/aggregate.py — AggregateFn protocol
(init/accumulate/merge/finalize) with Count/Sum/Min/Max/Mean/Std built-ins;
partial aggregation runs per block in parallel tasks, merge happens at the
consumer (map-side combine, the same two-stage shape as the reference's
shuffle-based aggregate).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


class AggregateFn:
    def __init__(self, init: Callable[[], Any],
                 accumulate_block: Callable[[Any, Any], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Callable[[Any], Any], name: str):
        self.init = init
        self.accumulate_block = accumulate_block
        self.merge = merge
        self.finalize = finalize
        self.name = name


class Count(AggregateFn):
    def __init__(self):
        super().__init__(
            init=lambda: 0,
            accumulate_block=lambda acc, col: acc + len(col),
            merge=lambda a, b: a + b,
            finalize=lambda acc: acc,
            name="count()")


class Sum(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: 0.0,
            accumulate_block=lambda acc, col: acc + float(np.sum(col)),
            merge=lambda a, b: a + b,
            finalize=lambda acc: acc,
            name=f"sum({on})")


class Min(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: float("inf"),
            accumulate_block=lambda acc, col: min(acc, float(np.min(col)))
            if len(col) else acc,
            merge=min,
            finalize=lambda acc: acc,
            name=f"min({on})")


class Max(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: float("-inf"),
            accumulate_block=lambda acc, col: max(acc, float(np.max(col)))
            if len(col) else acc,
            merge=max,
            finalize=lambda acc: acc,
            name=f"max({on})")


class Mean(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: (0.0, 0),
            accumulate_block=lambda acc, col: (acc[0] + float(np.sum(col)),
                                               acc[1] + len(col)),
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            finalize=lambda acc: acc[0] / acc[1] if acc[1] else None,
            name=f"mean({on})")


class Std(AggregateFn):
    """Chan/Welford parallel variance: mergeable (n, mean, M2) sketch —
    numerically stable where the naive sum/sum-of-squares formula
    catastrophically cancels on large-mean data."""

    def __init__(self, on: str, ddof: int = 1):
        self.on = on

        def acc_block(acc, col):
            bn = len(col)
            if bn == 0:
                return acc
            col = np.asarray(col, dtype=np.float64)
            bmean = float(np.mean(col))
            bM2 = float(np.sum((col - bmean) ** 2))
            return merge(acc, (bn, bmean, bM2))

        def merge(a, b):
            n1, m1, M1 = a
            n2, m2, M2 = b
            if n1 == 0:
                return b
            if n2 == 0:
                return a
            n = n1 + n2
            delta = m2 - m1
            return (n, m1 + delta * n2 / n,
                    M1 + M2 + delta * delta * n1 * n2 / n)

        def fin(acc):
            n, _, M2 = acc
            if n <= ddof:
                return None
            return float(np.sqrt(max(0.0, M2 / (n - ddof))))

        super().__init__(
            init=lambda: (0, 0.0, 0.0),
            accumulate_block=acc_block,
            merge=merge,
            finalize=fin,
            name=f"std({on})")
