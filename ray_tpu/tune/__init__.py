"""ray_tpu.tune: hyperparameter search over trial actors.

Reference: python/ray/tune/ — Tuner.fit (tuner.py:320) → TuneController
event loop (execution/tune_controller.py:49,267) over Trainable actors,
search algorithms (search/basic_variant.py grid/random), trial schedulers
(schedulers/async_hyperband.py ASHA), trial FSM (experiment/trial.py).

    from ray_tpu import tune

    def objective(config):
        for step in range(10):
            tune.report({"score": step * config["lr"]})

    results = tune.Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.1, 0.01])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    ).fit()
    best = results.get_best_result()
"""

from ray_tpu.tune.search import (AxSearch, BasicVariantGenerator,
                                 ConcurrencyLimiter, HyperOptSearch,
                                 OptunaSearch, TuneBOHB,
                                 BayesOptSearch, RandomSearch, Searcher,
                                 TPESearcher, choice,
                                 grid_search, loguniform, randint, uniform)
from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     HyperBandScheduler, MedianStoppingRule,
                                     PopulationBasedTraining)
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig, Tuner,
                                get_checkpoint, get_trial_context, report)
from ray_tpu.tune.loggers import (CSVLoggerCallback, JsonLoggerCallback,
                                  LoggerCallback, MLflowLoggerCallback,
                                  TBXLoggerCallback, WandbLoggerCallback)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TrialResult", "report",
    "get_checkpoint", "get_trial_context", "grid_search", "choice",
    "uniform", "loguniform", "randint", "ASHAScheduler", "FIFOScheduler",
    "HyperBandScheduler", "MedianStoppingRule", "PopulationBasedTraining",
    "Searcher", "BasicVariantGenerator", "RandomSearch", "TPESearcher",
    "BayesOptSearch",
    "ConcurrencyLimiter",
    "OptunaSearch", "HyperOptSearch", "TuneBOHB", "AxSearch",
    "LoggerCallback", "CSVLoggerCallback", "JsonLoggerCallback",
    "TBXLoggerCallback", "MLflowLoggerCallback", "WandbLoggerCallback",
]
