"""Tuner + trial controller.

Reference: tune/tuner.py:320 Tuner.fit → execution/tune_controller.py event
loop (step:267, actor scheduling :596): trials run as actors; the controller
polls reported results, feeds the scheduler, stops losers, and starts queued
trials as resources free up. Experiment state is snapshotted to the run dir
(ref: tune/execution/experiment_state.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP
from ray_tpu.tune.search import generate_variants


# ---- in-trial reporting API -------------------------------------------------

class _TrialContext:
    def __init__(self, trial_id: str, config: dict,
                 start_checkpoint: Any = None):
        self.trial_id = trial_id
        self.config = config
        self.reports: List[dict] = []
        self.lock = threading.Lock()
        self.iteration = 0
        self.stop_requested = False
        self.start_checkpoint = start_checkpoint
        self.latest_checkpoint: Any = None
        self.checkpoint_version = 0


_trial_ctx: Optional[_TrialContext] = None


def _set_trial_ctx(ctx: Optional[_TrialContext]) -> None:
    # NOTE: must be a module function called by reference. The @remote actor
    # class below ships to workers pickled BY VALUE (the module attribute is
    # the ActorClass wrapper, so cloudpickle cannot pickle the raw class by
    # reference), which gives its methods a COPY of these globals — a bare
    # `global` assignment inside a method would write to the copy while
    # tune.report reads the real module.
    global _trial_ctx
    _trial_ctx = ctx


def get_trial_context() -> Optional[_TrialContext]:
    return _trial_ctx


class TrialStopped(Exception):
    """Raised inside a trial when the scheduler has stopped it."""


def report(metrics: Dict[str, Any], checkpoint: Any = None) -> None:
    """ref: tune report / session.report — also the scheduler's stop
    injection point: raises TrialStopped if the controller killed us.
    `checkpoint` (any picklable payload, e.g. a params dict) enables
    PBT exploit transfer and restore."""
    ctx = _trial_ctx
    if ctx is None:
        raise RuntimeError("tune.report called outside a trial")
    ctx.iteration += 1
    entry = dict(metrics)
    entry.setdefault("training_iteration", ctx.iteration)
    entry["_ts"] = time.time()
    with ctx.lock:
        ctx.reports.append(entry)
        if checkpoint is not None:
            ctx.latest_checkpoint = checkpoint
            ctx.checkpoint_version += 1
    if ctx.stop_requested:
        raise TrialStopped()


def get_checkpoint() -> Any:
    """Checkpoint handed to this trial at start (PBT exploit or restore);
    None on a fresh start. ref: train.get_checkpoint in function trainables."""
    ctx = _trial_ctx
    if ctx is None:
        raise RuntimeError("tune.get_checkpoint called outside a trial")
    return ctx.start_checkpoint


@ray_tpu.remote
class _TrialActor:
    def __init__(self, trial_id: str, config: dict,
                 start_checkpoint: Any = None):
        self.ctx = _TrialContext(trial_id, config, start_checkpoint)
        self.error: Optional[str] = None
        self.done = False
        self.final: Any = None

    def run(self, fn: Callable) -> Any:
        _set_trial_ctx(self.ctx)
        try:
            self.final = fn(self.ctx.config)
            if isinstance(self.final, dict):
                with self.ctx.lock:
                    entry = dict(self.final)
                    entry.setdefault("training_iteration",
                                     self.ctx.iteration + 1)
                    self.ctx.reports.append(entry)
            return self.final
        except TrialStopped:
            return None
        except BaseException:
            import traceback

            self.error = traceback.format_exc()
            raise
        finally:
            self.done = True

    def poll(self, after: int, ckpt_seen: int = -1) -> dict:
        with self.ctx.lock:
            new = self.ctx.reports[after:]
            out = {"reports": new, "done": self.done, "error": self.error,
                   "ckpt_version": self.ctx.checkpoint_version}
            if self.ctx.checkpoint_version > ckpt_seen >= 0:
                out["checkpoint"] = self.ctx.latest_checkpoint
        return out

    def request_stop(self):
        self.ctx.stop_requested = True
        return True


# ---- results ----------------------------------------------------------------

@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[dict] = field(default_factory=list)
    error: Optional[str] = None
    stopped_early: bool = False


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric configured")

        def last_value(r: TrialResult):
            if metric in r.metrics:
                return r.metrics[metric]
            for entry in reversed(r.metrics_history):
                if metric in entry:
                    return entry[metric]
            return None

        valid = [(r, last_value(r)) for r in self._results]
        valid = [(r, v) for r, v in valid if v is not None]
        if not valid:
            raise ValueError(f"no trial reported metric {metric!r}")
        best = (max if mode == "max" else min)(valid, key=lambda rv: rv[1])
        return best[0]

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([{**r.config, **r.metrics,
                              "trial_id": r.trial_id} for r in self._results])

    @property
    def errors(self) -> List[TrialResult]:
        return [r for r in self._results if r.error]


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Any = None  # a tune.search.Searcher (ask/tell); None = basic variants
    seed: int = 0
    resources_per_trial: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config
        #: experiment ledger: trial_id -> {config, status, metrics, error,
        #: stopped_early, has_ckpt}; mirrored to experiment_state.json after
        #: every transition so a killed driver can be restored
        self._exp: Dict[str, dict] = {}
        self._restored = False

    # ---- experiment-state persistence (ref: tune/tuner.py:180 restore +
    #      tune/execution/experiment_state.py snapshots) ------------------

    @classmethod
    def restore(cls, path: str, trainable: Optional[Callable] = None,
                *, resume_errored: bool = False) -> "Tuner":
        """Recover a sweep whose driver died (ref: Tuner.restore,
        python/ray/tune/tuner.py:180). `path` is the run dir
        (storage_path/name). Completed trials keep their results;
        queued/running trials are re-launched, running ones from their
        last persisted checkpoint. Pass `trainable` when the original one
        doesn't pickle; `resume_errored` also re-runs failed trials."""
        import pickle

        with open(os.path.join(path, "tuner.pkl"), "rb") as f:
            meta = pickle.load(f)
        if trainable is None:
            try:
                import cloudpickle

                with open(os.path.join(path, "trainable.pkl"), "rb") as f:
                    trainable = cloudpickle.load(f)
            except Exception as e:
                raise ValueError(
                    "the original trainable could not be recovered from "
                    f"{path} ({e}); pass Tuner.restore(path, "
                    "trainable=...)") from e
        tuner = cls(trainable, param_space=meta["param_space"] or {},
                    tune_config=meta["tune_config"] or TuneConfig(),
                    run_config=meta["run_config"])
        with open(os.path.join(path, "experiment_state.json")) as f:
            tuner._exp = json.load(f)["trials"]
        # configs round-trip through pickle, not JSON — json.dump(default=
        # str) stringifies non-JSON values (np dtypes, tuples) and a
        # restored trial must see exactly what the original saw
        cfgs = os.path.join(path, "configs.pkl")
        if os.path.exists(cfgs):
            with open(cfgs, "rb") as f:
                side = pickle.load(f)
            if "configs" not in side:               # pre-r3 format
                side = {"configs": side, "metrics": {}}
            for tid, cfg in side["configs"].items():
                if tid in tuner._exp:
                    tuner._exp[tid]["config"] = cfg
            for tid, mets in side["metrics"].items():
                if tid in tuner._exp:
                    tuner._exp[tid]["metrics"] = mets
        ctrl = os.path.join(path, "controller.pkl")
        if os.path.exists(ctrl):  # searcher/scheduler mid-sweep state
            try:
                with open(ctrl, "rb") as f:
                    st = pickle.load(f)
                if st.get("searcher") is not None:
                    tuner.tune_config.search_alg = st["searcher"]
                if st.get("scheduler") is not None:
                    tuner.tune_config.scheduler = st["scheduler"]
            except Exception:
                pass  # fall back to fresh searcher over remaining trials
        if resume_errored:
            for rec in tuner._exp.values():
                if rec["status"] == "done" and rec.get("error"):
                    rec.update(status="queued", error=None, metrics={})
        tuner._restored = True
        # restore() must point at the same run dir
        if tuner.run_config is None or not getattr(
                tuner.run_config, "storage_path", None):
            from ray_tpu.train.config import RunConfig

            # abspath: dirname of a bare relative run dir is "" which
            # would silently disable all persistence for the restored run
            apath = os.path.abspath(path.rstrip("/"))
            tuner.run_config = RunConfig(
                name=os.path.basename(apath),
                storage_path=os.path.dirname(apath))
        return tuner

    def _snapshot(self, run_dir: Optional[str]) -> None:
        import pickle

        if not run_dir:
            return
        tmp = os.path.join(run_dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump({"trials": self._exp}, f, indent=2, default=str)
        os.replace(tmp, os.path.join(run_dir, "experiment_state.json"))
        # exact (typed) configs AND metrics ride a pickle sidecar — the
        # json (default=str) stringifies np/jnp scalars, and a restored
        # trial must see exactly what the original saw; the json stays
        # human-readable for status polling
        tmp2 = os.path.join(run_dir, ".configs.tmp")

        def picklable(tree: dict) -> dict:
            # drop only the offending entries — one unpicklable metric
            # value must not discard every typed config
            out = {}
            for tid, val in tree.items():
                try:
                    pickle.dumps(val)
                    out[tid] = val
                except Exception:
                    pass
            return out

        try:
            with open(tmp2, "wb") as f:
                pickle.dump({"configs": picklable(
                    {tid: rec["config"] for tid, rec in self._exp.items()}),
                    "metrics": picklable(
                    {tid: rec["metrics"] for tid, rec in self._exp.items()})},
                    f)
            os.replace(tmp2, os.path.join(run_dir, "configs.pkl"))
        except Exception:
            pass  # sidecar is best-effort: restore falls back to json

    def _save_meta(self, run_dir: Optional[str]) -> None:
        import pickle

        if not run_dir:
            return
        try:
            # by-value for __main__/script functions, same as task export
            from ray_tpu.core.runtime import _dumps_function

            blob = _dumps_function(self.trainable)
            with open(os.path.join(run_dir, "trainable.pkl"), "wb") as f:
                f.write(blob)
        except Exception:
            pass  # restore() will require an explicit trainable
        meta = {}
        for key, val in (("param_space", self.param_space),
                         ("tune_config", self.tune_config),
                         ("run_config", self.run_config)):
            try:
                pickle.dumps(val)
                meta[key] = val
            except Exception:
                # unpicklable scheduler/callback/etc: the run proceeds,
                # restore degrades to defaults for this piece
                meta[key] = None
        with open(os.path.join(run_dir, "tuner.pkl"), "wb") as f:
            pickle.dump(meta, f)

    def _save_controller(self, run_dir: Optional[str], searcher,
                         scheduler) -> None:
        import pickle

        if not run_dir:
            return
        try:
            blob = pickle.dumps({"searcher": searcher,
                                 "scheduler": scheduler})
        except Exception:
            return  # unpicklable searcher: restore falls back to fresh
        tmp = os.path.join(run_dir, ".controller.tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, os.path.join(run_dir, "controller.pkl"))

    def _ckpt_file(self, run_dir: str, tid: str) -> str:
        return os.path.join(run_dir, f"ckpt_{tid}.pkl")

    def _persist_trial_ckpt(self, run_dir: Optional[str], tid: str,
                            payload: Any) -> None:
        import pickle

        if not run_dir:
            return
        tmp = self._ckpt_file(run_dir, tid) + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, self._ckpt_file(run_dir, tid))
            if not self._exp[tid].get("has_ckpt"):
                self._exp[tid]["has_ckpt"] = True
                self._snapshot(run_dir)
        except Exception:
            pass  # unpicklable payload: restore starts the trial fresh

    def _load_trial_ckpt(self, run_dir: Optional[str], tid: str) -> Any:
        import pickle

        if not run_dir:
            return None
        try:
            with open(self._ckpt_file(run_dir, tid), "rb") as f:
                return pickle.load(f)
        except Exception:
            return None

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        if getattr(scheduler, "metric", None) is None and hasattr(scheduler, "metric"):
            scheduler.metric = tc.metric
        searcher = tc.search_alg
        run_dir = self._run_dir()
        if run_dir:
            os.makedirs(run_dir, exist_ok=True)
            self._save_meta(run_dir)
        results: Dict[str, TrialResult] = {}
        if searcher is not None:
            # also on restore: the searcher may be a fresh instance (no
            # controller.pkl yet) that never saw the space; adapters keep
            # their own space when the incoming one is empty
            searcher.set_search_properties(tc.metric, tc.mode,
                                           self.param_space)
            total = tc.num_samples
            pending: List = []  # searcher asked on demand
        else:
            if not self._restored:
                variants = generate_variants(self.param_space,
                                             tc.num_samples, tc.seed)
                self._exp = {f"trial_{i:05d}": {"config": cfg,
                                                "status": "queued",
                                                "metrics": {}, "error": None,
                                                "stopped_early": False,
                                                "has_ckpt": False}
                             for i, cfg in enumerate(variants)}
                self._snapshot(run_dir)
            total = len(self._exp)
            pending = [(tid, rec["config"])
                       for tid, rec in sorted(self._exp.items())
                       if rec["status"] != "done"]
        if self._restored:
            # completed trials keep their recorded results (never re-run);
            # queued/running ones re-enter the queue, running-with-ckpt
            # resume from their persisted checkpoint payload
            for tid, rec in sorted(self._exp.items()):
                if rec["status"] == "done":
                    results[tid] = TrialResult(
                        tid, rec["config"], metrics=rec["metrics"],
                        error=rec["error"],
                        stopped_early=rec.get("stopped_early", False))
                elif searcher is not None:
                    pending.append((tid, rec["config"]))
        max_conc = tc.max_concurrent_trials or max(1, total)
        # with an explicit queue the launch budget is the queue itself
        launched = len(self._exp) if searcher is not None else total
        running: Dict[str, dict] = {}
        # logger callbacks (ref: RunConfig.callbacks → tune/logger/*)
        callbacks = list(getattr(self.run_config, "callbacks", None) or [])
        if callbacks:
            cb_dir = self._run_dir() or os.path.join(
                os.path.expanduser("~/ray_tpu_results"),
                f"tune_{int(time.time())}")
            os.makedirs(cb_dir, exist_ok=True)
            for cb in callbacks:
                cb.setup(cb_dir)
        started: set = set()

        def launch(trial_id: str, cfg: dict, start_checkpoint=None):
            if trial_id not in started:
                started.add(trial_id)
                for cb in callbacks:
                    cb.on_trial_start(trial_id, cfg)
            rec = self._exp.setdefault(
                trial_id, {"config": cfg, "status": "queued", "metrics": {},
                           "error": None, "stopped_early": False,
                           "has_ckpt": False})
            if start_checkpoint is None and rec.get("has_ckpt"):
                # driver restored mid-sweep: trial resumes from its last
                # persisted checkpoint payload
                start_checkpoint = self._load_trial_ckpt(run_dir, trial_id)
            elif start_checkpoint is not None:
                # PBT exploit hands this trial the SOURCE's checkpoint: it
                # must land in ckpt_<tid>.pkl now, or a driver crash before
                # the first post-exploit checkpoint restores the stale
                # pre-exploit weights under the new config
                self._persist_trial_ckpt(run_dir, trial_id, start_checkpoint)
            rec["status"] = "running"
            rec["config"] = cfg
            self._snapshot(run_dir)
            actor = _TrialActor.options(
                resources=dict(tc.resources_per_trial),
                max_concurrency=2).remote(trial_id, cfg, start_checkpoint)
            run_ref = actor.run.remote(self.trainable)
            prev = running.get(trial_id)
            running[trial_id] = {"actor": actor, "run_ref": run_ref,
                                 "seen": 0, "ckpt_seen": 0,
                                 "checkpoint": prev["checkpoint"] if prev else None,
                                 "result": prev["result"] if prev
                                 else TrialResult(trial_id, cfg)}
            running[trial_id]["result"].config = cfg

        def finish(tid: str, res: TrialResult, error: bool):
            results[tid] = res
            self._exp[tid].update(status="done", metrics=res.metrics,
                                  error=res.error,
                                  stopped_early=res.stopped_early)
            self._snapshot(run_dir)
            for cb in callbacks:
                cb.on_trial_complete(tid, res)
            if searcher is not None:
                searcher.on_trial_complete(
                    tid, {**res.metrics, "config": res.config}, error=error)
            self._save_controller(run_dir, searcher, scheduler)

        # ---- controller loop (ref: tune_controller.step:267) ----
        while pending or running or launched < total:
            # fill free slots: from the explicit queue or the searcher
            while len(running) < max_conc:
                if pending:
                    tid, cfg = pending.pop(0)
                    launch(tid, cfg)
                elif searcher is not None and launched < total:
                    tid = f"trial_{launched:05d}"
                    cfg = searcher.suggest(tid)
                    if cfg is None:
                        total = launched  # searcher exhausted
                        break
                    if cfg == "PENDING":
                        break  # concurrency-limited; retry next tick
                    launch(tid, cfg)
                    launched += 1
                else:
                    break
            time.sleep(0.05)
            for tid in list(running):
                st = running[tid]
                try:
                    poll = ray_tpu.get(
                        st["actor"].poll.remote(st["seen"], st["ckpt_seen"]),
                        timeout=30)
                except Exception as e:
                    res = st["result"]
                    res.error = f"trial actor lost: {e}"
                    del running[tid]
                    finish(tid, res, error=True)
                    continue
                if "checkpoint" in poll:
                    st["checkpoint"] = poll["checkpoint"]
                    st["ckpt_seen"] = poll["ckpt_version"]
                    self._persist_trial_ckpt(run_dir, tid,
                                             poll["checkpoint"])
                res = st["result"]
                exploit = None
                for r in poll["reports"]:
                    r = {**r, "config": res.config}
                    res.metrics_history.append(r)
                    res.metrics = r
                    for cb in callbacks:
                        cb.on_trial_result(tid, r)
                    decision = scheduler.on_result(tid, r)
                    if decision == STOP and not poll["done"]:
                        try:
                            # advisory stop; a get() here could block the
                            # whole tuner loop behind one hung trial
                            # raylint: disable=leaked-object-ref -- advisory
                            st["actor"].request_stop.remote()
                        except Exception:
                            pass
                        res.stopped_early = True
                    elif isinstance(decision, tuple) and decision[0] == "EXPLOIT":
                        exploit = decision
                st["seen"] += len(poll["reports"])
                if exploit is not None and not poll["done"]:
                    # PBT: restart this trial from the source's checkpoint
                    # with the explored config (ref: pbt.py _exploit).
                    _, source_tid, new_config = exploit
                    src = running.get(source_tid)
                    src_ckpt = src["checkpoint"] if src else None
                    if src_ckpt is not None:
                        try:
                            ray_tpu.kill(st["actor"])
                        except Exception:
                            pass
                        launch(tid, new_config, start_checkpoint=src_ckpt)
                        continue
                if poll["done"]:
                    if poll["error"] and "TrialStopped" not in poll["error"]:
                        res.error = poll["error"]
                    try:
                        ray_tpu.kill(st["actor"])
                    except Exception:
                        pass
                    del running[tid]
                    finish(tid, res, error=bool(res.error))
        ordered = [results[tid] for tid in sorted(results)]
        for cb in callbacks:
            cb.on_experiment_end(ordered)
        return ResultGrid(ordered, tc.metric, tc.mode)

    def _run_dir(self) -> Optional[str]:
        if self.run_config is not None:
            base = getattr(self.run_config, "storage_path", None)
            name = getattr(self.run_config, "name", None)
            if base and name:
                return os.path.join(base, name)
        return None
