"""Tuner + trial controller.

Reference: tune/tuner.py:320 Tuner.fit → execution/tune_controller.py event
loop (step:267, actor scheduling :596): trials run as actors; the controller
polls reported results, feeds the scheduler, stops losers, and starts queued
trials as resources free up. Experiment state is snapshotted to the run dir
(ref: tune/execution/experiment_state.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP
from ray_tpu.tune.search import generate_variants


# ---- in-trial reporting API -------------------------------------------------

class _TrialContext:
    def __init__(self, trial_id: str, config: dict,
                 start_checkpoint: Any = None):
        self.trial_id = trial_id
        self.config = config
        self.reports: List[dict] = []
        self.lock = threading.Lock()
        self.iteration = 0
        self.stop_requested = False
        self.start_checkpoint = start_checkpoint
        self.latest_checkpoint: Any = None
        self.checkpoint_version = 0


_trial_ctx: Optional[_TrialContext] = None


def _set_trial_ctx(ctx: Optional[_TrialContext]) -> None:
    # NOTE: must be a module function called by reference. The @remote actor
    # class below ships to workers pickled BY VALUE (the module attribute is
    # the ActorClass wrapper, so cloudpickle cannot pickle the raw class by
    # reference), which gives its methods a COPY of these globals — a bare
    # `global` assignment inside a method would write to the copy while
    # tune.report reads the real module.
    global _trial_ctx
    _trial_ctx = ctx


def get_trial_context() -> Optional[_TrialContext]:
    return _trial_ctx


class TrialStopped(Exception):
    """Raised inside a trial when the scheduler has stopped it."""


def report(metrics: Dict[str, Any], checkpoint: Any = None) -> None:
    """ref: tune report / session.report — also the scheduler's stop
    injection point: raises TrialStopped if the controller killed us.
    `checkpoint` (any picklable payload, e.g. a params dict) enables
    PBT exploit transfer and restore."""
    ctx = _trial_ctx
    if ctx is None:
        raise RuntimeError("tune.report called outside a trial")
    ctx.iteration += 1
    entry = dict(metrics)
    entry.setdefault("training_iteration", ctx.iteration)
    entry["_ts"] = time.time()
    with ctx.lock:
        ctx.reports.append(entry)
        if checkpoint is not None:
            ctx.latest_checkpoint = checkpoint
            ctx.checkpoint_version += 1
    if ctx.stop_requested:
        raise TrialStopped()


def get_checkpoint() -> Any:
    """Checkpoint handed to this trial at start (PBT exploit or restore);
    None on a fresh start. ref: train.get_checkpoint in function trainables."""
    ctx = _trial_ctx
    if ctx is None:
        raise RuntimeError("tune.get_checkpoint called outside a trial")
    return ctx.start_checkpoint


@ray_tpu.remote
class _TrialActor:
    def __init__(self, trial_id: str, config: dict,
                 start_checkpoint: Any = None):
        self.ctx = _TrialContext(trial_id, config, start_checkpoint)
        self.error: Optional[str] = None
        self.done = False
        self.final: Any = None

    def run(self, fn: Callable) -> Any:
        _set_trial_ctx(self.ctx)
        try:
            self.final = fn(self.ctx.config)
            if isinstance(self.final, dict):
                with self.ctx.lock:
                    entry = dict(self.final)
                    entry.setdefault("training_iteration",
                                     self.ctx.iteration + 1)
                    self.ctx.reports.append(entry)
            return self.final
        except TrialStopped:
            return None
        except BaseException:
            import traceback

            self.error = traceback.format_exc()
            raise
        finally:
            self.done = True

    def poll(self, after: int, ckpt_seen: int = -1) -> dict:
        with self.ctx.lock:
            new = self.ctx.reports[after:]
            out = {"reports": new, "done": self.done, "error": self.error,
                   "ckpt_version": self.ctx.checkpoint_version}
            if self.ctx.checkpoint_version > ckpt_seen >= 0:
                out["checkpoint"] = self.ctx.latest_checkpoint
        return out

    def request_stop(self):
        self.ctx.stop_requested = True
        return True


# ---- results ----------------------------------------------------------------

@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[dict] = field(default_factory=list)
    error: Optional[str] = None
    stopped_early: bool = False


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric configured")

        def last_value(r: TrialResult):
            if metric in r.metrics:
                return r.metrics[metric]
            for entry in reversed(r.metrics_history):
                if metric in entry:
                    return entry[metric]
            return None

        valid = [(r, last_value(r)) for r in self._results]
        valid = [(r, v) for r, v in valid if v is not None]
        if not valid:
            raise ValueError(f"no trial reported metric {metric!r}")
        best = (max if mode == "max" else min)(valid, key=lambda rv: rv[1])
        return best[0]

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([{**r.config, **r.metrics,
                              "trial_id": r.trial_id} for r in self._results])

    @property
    def errors(self) -> List[TrialResult]:
        return [r for r in self._results if r.error]


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Any = None  # a tune.search.Searcher (ask/tell); None = basic variants
    seed: int = 0
    resources_per_trial: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        if getattr(scheduler, "metric", None) is None and hasattr(scheduler, "metric"):
            scheduler.metric = tc.metric
        searcher = tc.search_alg
        if searcher is not None:
            searcher.set_search_properties(tc.metric, tc.mode,
                                           self.param_space)
            total = tc.num_samples
            pending: List = []  # searcher asked on demand
        else:
            variants = generate_variants(self.param_space, tc.num_samples,
                                         tc.seed)
            total = len(variants)
            pending = [(f"trial_{i:05d}", cfg)
                       for i, cfg in enumerate(variants)]
        max_conc = tc.max_concurrent_trials or max(1, total)
        # with an explicit queue the launch budget is the queue itself
        launched = 0 if searcher is not None else total
        running: Dict[str, dict] = {}
        results: Dict[str, TrialResult] = {}
        # logger callbacks (ref: RunConfig.callbacks → tune/logger/*)
        callbacks = list(getattr(self.run_config, "callbacks", None) or [])
        if callbacks:
            cb_dir = self._run_dir() or os.path.join(
                os.path.expanduser("~/ray_tpu_results"),
                f"tune_{int(time.time())}")
            os.makedirs(cb_dir, exist_ok=True)
            for cb in callbacks:
                cb.setup(cb_dir)
        started: set = set()

        def launch(trial_id: str, cfg: dict, start_checkpoint=None):
            if trial_id not in started:
                started.add(trial_id)
                for cb in callbacks:
                    cb.on_trial_start(trial_id, cfg)
            actor = _TrialActor.options(
                resources=dict(tc.resources_per_trial),
                max_concurrency=2).remote(trial_id, cfg, start_checkpoint)
            run_ref = actor.run.remote(self.trainable)
            prev = running.get(trial_id)
            running[trial_id] = {"actor": actor, "run_ref": run_ref,
                                 "seen": 0, "ckpt_seen": 0,
                                 "checkpoint": prev["checkpoint"] if prev else None,
                                 "result": prev["result"] if prev
                                 else TrialResult(trial_id, cfg)}
            running[trial_id]["result"].config = cfg

        def finish(tid: str, res: TrialResult, error: bool):
            results[tid] = res
            for cb in callbacks:
                cb.on_trial_complete(tid, res)
            if searcher is not None:
                searcher.on_trial_complete(
                    tid, {**res.metrics, "config": res.config}, error=error)

        # ---- controller loop (ref: tune_controller.step:267) ----
        while pending or running or launched < total:
            # fill free slots: from the explicit queue or the searcher
            while len(running) < max_conc:
                if pending:
                    tid, cfg = pending.pop(0)
                    launch(tid, cfg)
                elif searcher is not None and launched < total:
                    tid = f"trial_{launched:05d}"
                    cfg = searcher.suggest(tid)
                    if cfg is None:
                        total = launched  # searcher exhausted
                        break
                    if cfg == "PENDING":
                        break  # concurrency-limited; retry next tick
                    launch(tid, cfg)
                    launched += 1
                else:
                    break
            time.sleep(0.05)
            for tid in list(running):
                st = running[tid]
                try:
                    poll = ray_tpu.get(
                        st["actor"].poll.remote(st["seen"], st["ckpt_seen"]),
                        timeout=30)
                except Exception as e:
                    res = st["result"]
                    res.error = f"trial actor lost: {e}"
                    del running[tid]
                    finish(tid, res, error=True)
                    continue
                if "checkpoint" in poll:
                    st["checkpoint"] = poll["checkpoint"]
                    st["ckpt_seen"] = poll["ckpt_version"]
                res = st["result"]
                exploit = None
                for r in poll["reports"]:
                    r = {**r, "config": res.config}
                    res.metrics_history.append(r)
                    res.metrics = r
                    for cb in callbacks:
                        cb.on_trial_result(tid, r)
                    decision = scheduler.on_result(tid, r)
                    if decision == STOP and not poll["done"]:
                        try:
                            st["actor"].request_stop.remote()
                        except Exception:
                            pass
                        res.stopped_early = True
                    elif isinstance(decision, tuple) and decision[0] == "EXPLOIT":
                        exploit = decision
                st["seen"] += len(poll["reports"])
                if exploit is not None and not poll["done"]:
                    # PBT: restart this trial from the source's checkpoint
                    # with the explored config (ref: pbt.py _exploit).
                    _, source_tid, new_config = exploit
                    src = running.get(source_tid)
                    src_ckpt = src["checkpoint"] if src else None
                    if src_ckpt is not None:
                        try:
                            ray_tpu.kill(st["actor"])
                        except Exception:
                            pass
                        launch(tid, new_config, start_checkpoint=src_ckpt)
                        continue
                if poll["done"]:
                    if poll["error"] and "TrialStopped" not in poll["error"]:
                        res.error = poll["error"]
                    try:
                        ray_tpu.kill(st["actor"])
                    except Exception:
                        pass
                    del running[tid]
                    finish(tid, res, error=bool(res.error))
        ordered = [results[tid] for tid in sorted(results)]
        for cb in callbacks:
            cb.on_experiment_end(ordered)
        self._save_experiment_state(ordered)
        return ResultGrid(ordered, tc.metric, tc.mode)

    def _run_dir(self) -> Optional[str]:
        if self.run_config is not None:
            base = getattr(self.run_config, "storage_path", None)
            name = getattr(self.run_config, "name", None)
            if base and name:
                return os.path.join(base, name)
        return None

    def _save_experiment_state(self, results: List[TrialResult]):
        run_dir = self._run_dir()
        if run_dir is None:
            return
        os.makedirs(run_dir, exist_ok=True)
        state = [{"trial_id": r.trial_id, "config": r.config,
                  "metrics": r.metrics, "error": r.error,
                  "stopped_early": r.stopped_early} for r in results]
        with open(os.path.join(run_dir, "experiment_state.json"), "w") as f:
            json.dump(state, f, indent=2, default=str)
