"""Search space + variant generation.

Reference: python/ray/tune/search/{sample.py,basic_variant.py} — grid_search
cross products with random sampling for distribution params.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


class Sampler:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Choice(Sampler):
    def __init__(self, values):
        self.values = list(values)

    def sample(self, rng):
        return rng.choice(self.values)


class Uniform(Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class LogUniform(Sampler):
    def __init__(self, lo, hi):
        import math

        self.lo, self.hi = math.log(lo), math.log(hi)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randrange(self.lo, self.hi)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def choice(values) -> Choice:
    return Choice(values)


def uniform(lo, hi) -> Uniform:
    return Uniform(lo, hi)


def loguniform(lo, hi) -> LogUniform:
    return LogUniform(lo, hi)


def randint(lo, hi) -> RandInt:
    return RandInt(lo, hi)


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Grid params cross-product; sampler params drawn per sample
    (ref: basic_variant.py — num_samples repeats the grid)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids = [param_space[k].values for k in grid_keys]
    combos = list(itertools.product(*grids)) if grid_keys else [()]
    variants = []
    for _ in range(max(1, num_samples)):
        for combo in combos:
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Sampler):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
