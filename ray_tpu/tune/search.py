"""Search space + variant generation.

Reference: python/ray/tune/search/{sample.py,basic_variant.py} — grid_search
cross products with random sampling for distribution params.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Optional


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


class Sampler:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Choice(Sampler):
    def __init__(self, values):
        self.values = list(values)

    def sample(self, rng):
        return rng.choice(self.values)


class Uniform(Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class LogUniform(Sampler):
    def __init__(self, lo, hi):
        import math

        self.lo, self.hi = math.log(lo), math.log(hi)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randrange(self.lo, self.hi)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def choice(values) -> Choice:
    return Choice(values)


def uniform(lo, hi) -> Uniform:
    return Uniform(lo, hi)


def loguniform(lo, hi) -> LogUniform:
    return LogUniform(lo, hi)


def randint(lo, hi) -> RandInt:
    return RandInt(lo, hi)


def sample_config(param_space: Dict[str, Any], rng: random.Random,
                  grid_combo: Dict[str, Any] | None = None) -> Dict[str, Any]:
    cfg = {}
    for k, v in param_space.items():
        if isinstance(v, GridSearch):
            cfg[k] = (grid_combo or {}).get(k, rng.choice(v.values))
        elif isinstance(v, Sampler):
            cfg[k] = v.sample(rng)
        else:
            cfg[k] = v
    return cfg


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Grid params cross-product; sampler params drawn per sample
    (ref: basic_variant.py — num_samples repeats the grid)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids = [param_space[k].values for k in grid_keys]
    combos = list(itertools.product(*grids)) if grid_keys else [()]
    variants = []
    for _ in range(max(1, num_samples)):
        for combo in combos:
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Sampler):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants


# ---- searcher API -----------------------------------------------------------
# Reference: python/ray/tune/search/searcher.py — Searcher.suggest /
# on_trial_complete drive ask/tell search algorithms (Optuna, HyperOpt, ...).
# Here the algorithms are implemented natively instead of wrapping third-party
# libraries.

class Searcher:
    """Ask/tell interface: the Tuner calls suggest() to obtain configs and
    on_trial_complete() with the final result."""

    def __init__(self, metric: str | None = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: str | None, mode: str,
                              param_space: Dict[str, Any]) -> None:
        if self.metric is None:
            self.metric = metric
        if mode:
            self.mode = mode
        self.param_space = param_space

    def suggest(self, trial_id: str) -> Dict[str, Any] | None:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product + random sampling (ref: search/basic_variant.py)."""

    def __init__(self, num_samples: int = 1, seed: int = 0):
        super().__init__()
        self.num_samples = num_samples
        self.seed = seed
        self._variants: List[Dict[str, Any]] | None = None
        self._next = 0

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self._variants = generate_variants(param_space, self.num_samples,
                                           self.seed)

    def suggest(self, trial_id):
        if self._variants is None or self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg


class RandomSearch(Searcher):
    """Pure random sampling from the space, unbounded (until num_samples
    trials have been asked for by the controller)."""

    def __init__(self, seed: int = 0):
        super().__init__()
        self.rng = random.Random(seed)

    def suggest(self, trial_id):
        return sample_config(self.param_space, self.rng)


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator (the algorithm behind the
    reference's OptunaSearch/HyperOptSearch defaults, implemented directly).

    Observations are split at the gamma-quantile into good/bad sets; numeric
    params are modeled as Parzen windows (gaussian KDE centered on past
    samples), categorical params as weighted categoricals; candidates are
    drawn from the good model and scored by the density ratio l(x)/g(x).
    """

    def __init__(self, metric: str | None = None, mode: str = "max",
                 n_startup_trials: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        super().__init__(metric, mode)
        self.n_startup = n_startup_trials
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._history: List[tuple[Dict[str, Any], float]] = []

    def on_trial_complete(self, trial_id, result=None, error=False):
        if error or not result or self.metric not in result:
            return
        val = float(result[self.metric])
        if self.mode == "min":
            val = -val
        self._history.append((result["config"], val))

    # -- per-parameter density models --
    def _split(self):
        ordered = sorted(self._history, key=lambda cv: -cv[1])
        n_good = max(1, int(len(ordered) * self.gamma))
        return ordered[:n_good], ordered[n_good:]

    @staticmethod
    def _kde_logpdf(x, centers, bw):
        import math

        if not centers:
            return 0.0
        acc = 0.0
        for c in centers:
            acc += math.exp(-0.5 * ((x - c) / bw) ** 2)
        return math.log(acc / len(centers) + 1e-12)

    def _score(self, key, spec, value, good, bad):
        import math

        gvals = [c[key] for c, _ in good if key in c]
        bvals = [c[key] for c, _ in bad if key in c]
        if isinstance(spec, (Choice, GridSearch)):
            values = spec.values
            gw = (gvals.count(value) + 1) / (len(gvals) + len(values))
            bw_ = (bvals.count(value) + 1) / (len(bvals) + len(values))
            return math.log(gw) - math.log(bw_)
        # numeric: bandwidth from the prior range
        if isinstance(spec, (Uniform, LogUniform, RandInt)):
            lo, hi = spec.lo, spec.hi
            x = math.log(value) if isinstance(spec, LogUniform) else value
            g_centers = [math.log(v) if isinstance(spec, LogUniform) else v
                         for v in gvals]
            b_centers = [math.log(v) if isinstance(spec, LogUniform) else v
                         for v in bvals]
            bw = max((hi - lo) / 5.0, 1e-9)
            return (self._kde_logpdf(x, g_centers, bw)
                    - self._kde_logpdf(x, b_centers, bw))
        return 0.0

    def _sample_from_good(self, key, spec, good):
        """Draw from the good-set Parzen model (fall back to the prior)."""
        gvals = [c[key] for c, _ in good if key in c]
        if not gvals or self.rng.random() < 0.2:
            return sample_config({key: spec}, self.rng)[key]
        if isinstance(spec, (Choice, GridSearch)):
            return self.rng.choice(gvals)
        if isinstance(spec, (Uniform, LogUniform, RandInt)):
            import math

            lo, hi = spec.lo, spec.hi
            center = self.rng.choice(gvals)
            x = math.log(center) if isinstance(spec, LogUniform) else center
            bw = max((hi - lo) / 5.0, 1e-9)
            x = self.rng.gauss(x, bw)
            x = max(lo, min(hi, x))
            if isinstance(spec, LogUniform):
                return math.exp(x)
            if isinstance(spec, RandInt):
                return int(round(max(spec.lo, min(spec.hi - 1, x))))
            return x
        return sample_config({key: spec}, self.rng)[key]

    def suggest(self, trial_id):
        tunable = {k: v for k, v in self.param_space.items()
                   if isinstance(v, (Sampler, GridSearch))}
        fixed = {k: v for k, v in self.param_space.items()
                 if not isinstance(v, (Sampler, GridSearch))}
        if len(self._history) < self.n_startup:
            return {**fixed, **sample_config(tunable, self.rng)}
        good, bad = self._split()
        best, best_score = None, float("-inf")
        for _ in range(self.n_candidates):
            cand = {k: self._sample_from_good(k, v, good)
                    for k, v in tunable.items()}
            score = sum(self._score(k, v, cand[k], good, bad)
                        for k, v in tunable.items())
            if score > best_score:
                best, best_score = cand, score
        return {**fixed, **best}


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (ref: search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self.searcher.set_search_properties(metric, mode, param_space)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return "PENDING"
        cfg = self.searcher.suggest(trial_id)
        if isinstance(cfg, dict):
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class BayesOptSearch(Searcher):
    """GP-based Bayesian optimization with Expected Improvement
    (ref: search/bayesopt/bayesopt_search.py — the reference wraps the
    `bayesian-optimization` package; this is the same GP+EI loop on
    sklearn's GaussianProcessRegressor, which the TPU image carries).

    Numeric params (Uniform/LogUniform/RandInt) are modeled in a unit
    hypercube (log-space for LogUniform); Choice params are sampled
    randomly per suggestion (categorical kernels are out of scope, as in
    the reference's wrapper).
    """

    def __init__(self, metric: str | None = None, mode: str = "max",
                 n_startup_trials: int = 6, n_candidates: int = 256,
                 xi: float = 0.01, seed: int = 0):
        super().__init__(metric, mode)
        self.n_startup = n_startup_trials
        self.n_candidates = n_candidates
        self.xi = xi
        import numpy as _np

        self._np = _np
        self.rng = _np.random.default_rng(seed)
        self._pyrng = random.Random(seed)
        self._X: List[List[float]] = []
        self._y: List[float] = []

    def _numeric_keys(self):
        out = []
        for k, v in sorted(self.param_space.items()):
            if isinstance(v, (Uniform, LogUniform, RandInt)):
                out.append((k, v))
        return out

    def _encode(self, cfg) -> List[float]:
        import math

        x = []
        for k, spec in self._numeric_keys():
            v = float(cfg[k])
            if isinstance(spec, LogUniform):
                x.append((math.log(v) - spec.lo) / (spec.hi - spec.lo))
            elif isinstance(spec, RandInt):
                x.append((v - spec.lo) / max(1, spec.hi - 1 - spec.lo))
            else:
                x.append((v - spec.lo) / (spec.hi - spec.lo))
        return x

    def _decode(self, x) -> Dict[str, Any]:
        import math

        cfg = {}
        for (k, spec), u in zip(self._numeric_keys(), x):
            u = min(1.0, max(0.0, float(u)))
            if isinstance(spec, LogUniform):
                cfg[k] = math.exp(spec.lo + u * (spec.hi - spec.lo))
            elif isinstance(spec, RandInt):
                cfg[k] = int(round(spec.lo + u * max(1, spec.hi - 1
                                                     - spec.lo)))
            else:
                cfg[k] = spec.lo + u * (spec.hi - spec.lo)
        return cfg

    def _non_numeric(self) -> Dict[str, Any]:
        cfg = {}
        for k, v in self.param_space.items():
            if isinstance(v, Choice):
                cfg[k] = v.sample(self._pyrng)
            elif isinstance(v, GridSearch):
                cfg[k] = self._pyrng.choice(v.values)
            elif not isinstance(v, Sampler):
                cfg[k] = v
        return cfg

    def suggest(self, trial_id):
        np = self._np
        keys = self._numeric_keys()
        if not keys:
            return {**self._non_numeric()}
        d = len(keys)
        if len(self._y) < self.n_startup:
            u = self.rng.random(d)
            return {**self._non_numeric(), **self._decode(u)}

        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import Matern

        gp = GaussianProcessRegressor(
            kernel=Matern(nu=2.5), alpha=1e-6, normalize_y=True,
            random_state=int(self.rng.integers(1 << 31)))
        gp.fit(np.asarray(self._X), np.asarray(self._y))
        cand = self.rng.random((self.n_candidates, d))
        mu, sigma = gp.predict(cand, return_std=True)
        best = max(self._y)
        sigma = np.maximum(sigma, 1e-9)
        z = (mu - best - self.xi) / sigma
        from scipy.stats import norm  # scipy ships with sklearn deps

        ei = (mu - best - self.xi) * norm.cdf(z) + sigma * norm.pdf(z)
        return {**self._non_numeric(),
                **self._decode(cand[int(np.argmax(ei))])}

    def on_trial_complete(self, trial_id, result=None, error=False):
        if error or not result or self.metric not in result:
            return
        val = float(result[self.metric])
        if self.mode == "min":
            val = -val
        cfg = result["config"]
        try:
            self._X.append(self._encode(cfg))
            self._y.append(val)
        except (KeyError, ValueError):
            pass


def _gated_searcher(name: str, package: str):
    """External-library searcher surface (ref: tune/search/{optuna,
    hyperopt,bohb,ax}.py — thin wrappers over optional packages). The
    TPU image ships none of them; constructing one raises with install
    guidance. In-image equivalents: TPESearcher (HyperOpt/Optuna-class
    TPE) and BayesOptSearch (GP+EI)."""

    class _Gated(Searcher):
        def __init__(self, *a, **k):
            raise ImportError(
                f"{name} needs the '{package}' package, which is not in "
                f"the TPU image. Install it in your driver environment, "
                f"or use the in-image TPESearcher / BayesOptSearch.")

    _Gated.__name__ = name
    _Gated.__qualname__ = name
    return _Gated


class OptunaSearch(Searcher):
    """Adapter over optuna's ask/tell Study API (ref:
    python/ray/tune/search/optuna/optuna_search.py). Our samplers map onto
    optuna distributions: Uniform→suggest_float, LogUniform→log float,
    RandInt→suggest_int, Choice/GridSearch→suggest_categorical. The image
    does not ship optuna; the class constructs against any module exposing
    create_study/ask/tell (exercised in CI via a mock), and against the
    real package when installed in a driver env."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 sampler=None, seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch needs the 'optuna' package, which is not in "
                "the TPU image. Install it in your driver environment, or "
                "use the in-image TPESearcher / BayesOptSearch.") from e
        self._optuna = optuna
        self.param_space = space or {}
        self._sampler = sampler
        self._seed = seed
        self._study = None
        self._trials: Dict[str, Any] = {}
        self._cfgs: Dict[str, dict] = {}     # trial_id -> suggested cfg
        #: completed observations (cfg, value, failed) — the picklable
        #: record of what the study has seen; replayed into a fresh study
        #: after Tuner.restore unpickles this searcher
        self._history: list = []

    # The live optuna module/Study/Trial objects don't pickle, which would
    # make Tuner's controller.pkl snapshot silently fail for this adapter.
    # Pickle the observation history instead and replay it on restore.
    def __getstate__(self):
        state = dict(self.__dict__)
        for k in ("_optuna", "_study", "_trials"):
            state.pop(k, None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        import optuna

        self._optuna = optuna
        self._study = None
        self._trials = {}

    def set_search_properties(self, metric, mode, param_space):
        if self.metric is None:
            self.metric = metric
        if mode:
            self.mode = mode
        # a constructor-provided space wins over an empty Tuner space
        # (ref: the reference adapter refuses to overwrite a set space)
        if param_space or not self.param_space:
            self.param_space = param_space

    def _ensure_study(self):
        if self.metric is None:
            raise ValueError(
                "OptunaSearch needs a metric (constructor or "
                "TuneConfig.metric) — without one every completed trial "
                "would be reported to optuna as failed")
        if self._study is None:
            optuna = self._optuna
            sampler = self._sampler
            if sampler is None and self._seed is not None:
                sampler = optuna.samplers.TPESampler(seed=self._seed)
            self._study = optuna.create_study(
                direction="maximize" if self.mode == "max" else "minimize",
                sampler=sampler)
            completed = [(cfg, value) for cfg, value, failed in self._history
                         if not failed and value is not None]
            if completed:
                try:
                    dists = self._distributions()
                    for cfg, value in completed:
                        self._study.add_trial(optuna.trial.create_trial(
                            params={k: v for k, v in cfg.items()
                                    if k in dists},
                            distributions=dists, value=value))
                except Exception:
                    # replay is best-effort: a study that forgot history
                    # still suggests valid configs
                    pass
        return self._study

    def _distributions(self):
        import math

        optuna = self._optuna
        dist = {}
        for k, v in self.param_space.items():
            if isinstance(v, LogUniform):
                dist[k] = optuna.distributions.FloatDistribution(
                    math.exp(v.lo), math.exp(v.hi), log=True)
            elif isinstance(v, Uniform):
                dist[k] = optuna.distributions.FloatDistribution(v.lo, v.hi)
            elif isinstance(v, RandInt):
                dist[k] = optuna.distributions.IntDistribution(v.lo, v.hi - 1)
            elif isinstance(v, (Choice, GridSearch)):
                dist[k] = optuna.distributions.CategoricalDistribution(
                    v.values)
        return dist

    def suggest(self, trial_id):
        study = self._ensure_study()
        t = study.ask()
        import math

        cfg = {}
        for k, v in self.param_space.items():
            if isinstance(v, LogUniform):
                cfg[k] = t.suggest_float(k, math.exp(v.lo), math.exp(v.hi),
                                         log=True)
            elif isinstance(v, Uniform):
                cfg[k] = t.suggest_float(k, v.lo, v.hi)
            elif isinstance(v, RandInt):
                cfg[k] = t.suggest_int(k, v.lo, v.hi - 1)
            elif isinstance(v, (Choice, GridSearch)):
                cfg[k] = t.suggest_categorical(k, v.values)
            else:
                cfg[k] = v
        self._trials[trial_id] = t
        self._cfgs[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        t = self._trials.pop(trial_id, None)
        cfg = self._cfgs.pop(trial_id, None)
        if t is None:
            return
        study = self._ensure_study()
        if error or not result or self.metric not in result:
            study.tell(t, state=self._optuna.trial.TrialState.FAIL)
            if cfg is not None:
                self._history.append((cfg, None, True))
        else:
            val = float(result[self.metric])
            study.tell(t, val)
            if cfg is not None:
                self._history.append((cfg, val, False))


HyperOptSearch = _gated_searcher("HyperOptSearch", "hyperopt")
TuneBOHB = _gated_searcher("TuneBOHB", "hpbandster")
AxSearch = _gated_searcher("AxSearch", "ax-platform")
