"""Tune logger callbacks: CSV, JSON-lines, TensorBoard + gated
integrations.

Reference: python/ray/tune/logger/ (logger.py LoggerCallback base,
csv.py CSVLoggerCallback, json.py JsonLoggerCallback, tensorboardx.py
TBXLoggerCallback) and python/ray/air/integrations/{mlflow,wandb}.py.
Callbacks ride RunConfig.callbacks and receive every trial report from
the Tuner controller loop (tuner.py), writing per-trial artifacts under
<run_dir>/<trial_id>/ exactly where the experiment state lives.
"""

from __future__ import annotations

import csv
import json
import os
import time
from typing import Any, Dict, List, Optional


def _scalars(result: Dict[str, Any]) -> Dict[str, float]:
    return {k: v for k, v in result.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


class LoggerCallback:
    """Hook surface (ref: tune/logger/logger.py LoggerCallback +
    tune/callback.py Callback — merged; the split there is historical)."""

    def setup(self, run_dir: str) -> None:
        pass

    def on_trial_start(self, trial_id: str, config: Dict[str, Any]) -> None:
        pass

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: Any) -> None:
        pass

    def on_experiment_end(self, results: List[Any]) -> None:
        pass


class _PerTrialDirCallback(LoggerCallback):
    def setup(self, run_dir: str) -> None:
        self.run_dir = run_dir

    def _trial_dir(self, trial_id: str) -> str:
        d = os.path.join(self.run_dir, trial_id)
        os.makedirs(d, exist_ok=True)
        return d


class CSVLoggerCallback(_PerTrialDirCallback):
    """progress.csv per trial (ref: tune/logger/csv.py). The header is
    fixed by the FIRST result's scalar keys; later extra keys are
    dropped, missing ones left blank — same behavior as the reference."""

    def setup(self, run_dir: str) -> None:
        super().setup(run_dir)
        self._writers: Dict[str, Any] = {}
        self._files: Dict[str, Any] = {}

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        row = _scalars(result)
        if trial_id not in self._writers:
            f = open(os.path.join(self._trial_dir(trial_id),
                                  "progress.csv"), "w", newline="")
            w = csv.DictWriter(f, fieldnames=list(row.keys()),
                               extrasaction="ignore")
            w.writeheader()
            self._files[trial_id], self._writers[trial_id] = f, w
        self._writers[trial_id].writerow(row)
        self._files[trial_id].flush()

    def on_trial_complete(self, trial_id: str, result: Any):
        f = self._files.pop(trial_id, None)
        if f:
            f.close()
        self._writers.pop(trial_id, None)

    def on_experiment_end(self, results: List[Any]):
        for f in self._files.values():
            f.close()
        self._files.clear()
        self._writers.clear()


class JsonLoggerCallback(_PerTrialDirCallback):
    """result.json (one JSON per line) + params.json per trial
    (ref: tune/logger/json.py)."""

    def on_trial_start(self, trial_id: str, config: Dict[str, Any]):
        with open(os.path.join(self._trial_dir(trial_id),
                               "params.json"), "w") as f:
            json.dump(config, f, default=str)

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        with open(os.path.join(self._trial_dir(trial_id),
                               "result.json"), "a") as f:
            f.write(json.dumps(result, default=str) + "\n")


class TBXLoggerCallback(_PerTrialDirCallback):
    """TensorBoard scalars per trial via tf.summary (ref:
    tune/logger/tensorboardx.py — tensorboardX there; tensorflow is in
    this image and writes the same event-file format)."""

    def setup(self, run_dir: str) -> None:
        super().setup(run_dir)
        try:
            import tensorflow as tf  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "TBXLoggerCallback needs tensorflow (for tf.summary); "
                "it is present in the standard TPU image") from e
        self._writers: Dict[str, Any] = {}

    def _writer(self, trial_id: str):
        import tensorflow as tf

        if trial_id not in self._writers:
            self._writers[trial_id] = tf.summary.create_file_writer(
                self._trial_dir(trial_id))
        return self._writers[trial_id]

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        import tensorflow as tf

        step = int(result.get("training_iteration",
                              result.get("step", 0)) or 0)
        with self._writer(trial_id).as_default():
            for k, v in _scalars(result).items():
                tf.summary.scalar(f"ray/tune/{k}", v, step=step)

    def on_trial_complete(self, trial_id: str, result: Any):
        w = self._writers.pop(trial_id, None)
        if w is not None:
            w.close()

    def on_experiment_end(self, results: List[Any]):
        for w in self._writers.values():
            w.close()
        self._writers.clear()


class MLflowLoggerCallback(LoggerCallback):
    """ref: air/integrations/mlflow.py — one MLflow run per trial.
    Gated: mlflow is not in the TPU image."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: str = "ray_tpu"):
        try:
            import mlflow  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "MLflowLoggerCallback needs the mlflow package; install "
                "it in your driver environment (it is not in the TPU "
                "image)") from e
        self.tracking_uri = tracking_uri
        self.experiment_name = experiment_name
        self._runs: Dict[str, Any] = {}

    def setup(self, run_dir: str) -> None:
        import mlflow

        if self.tracking_uri:
            mlflow.set_tracking_uri(self.tracking_uri)
        mlflow.set_experiment(self.experiment_name)

    def on_trial_start(self, trial_id: str, config: Dict[str, Any]):
        import mlflow

        run = mlflow.start_run(run_name=trial_id, nested=True)
        self._runs[trial_id] = run
        mlflow.log_params({k: str(v) for k, v in config.items()},
                          run_id=run.info.run_id)

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        import mlflow

        run = self._runs.get(trial_id)
        if run:
            mlflow.log_metrics(_scalars(result),
                               step=int(result.get("training_iteration",
                                                   0) or 0),
                               run_id=run.info.run_id)

    def on_trial_complete(self, trial_id: str, result: Any):
        import mlflow

        run = self._runs.pop(trial_id, None)
        if run:
            mlflow.end_run()


class WandbLoggerCallback(LoggerCallback):
    """ref: air/integrations/wandb.py — one W&B run per trial.
    Gated: wandb is not in the TPU image."""

    def __init__(self, project: str = "ray_tpu", **init_kwargs):
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbLoggerCallback needs the wandb package; install it "
                "in your driver environment (it is not in the TPU "
                "image)") from e
        self.project = project
        self.init_kwargs = init_kwargs
        self._runs: Dict[str, Any] = {}

    def on_trial_start(self, trial_id: str, config: Dict[str, Any]):
        import wandb

        self._runs[trial_id] = wandb.init(
            project=self.project, name=trial_id, config=config,
            reinit=True, **self.init_kwargs)

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        run = self._runs.get(trial_id)
        if run:
            run.log(_scalars(result))

    def on_trial_complete(self, trial_id: str, result: Any):
        run = self._runs.pop(trial_id, None)
        if run:
            run.finish()


__all__ = ["LoggerCallback", "CSVLoggerCallback", "JsonLoggerCallback",
           "TBXLoggerCallback", "MLflowLoggerCallback",
           "WandbLoggerCallback"]
