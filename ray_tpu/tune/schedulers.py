"""Trial schedulers.

Reference: python/ray/tune/schedulers/ — FIFOScheduler (no-op) and ASHA
(async_hyperband.py): asynchronous successive halving on reported metrics;
a trial that falls below the rung's top-1/reduction_factor quantile at a
milestone is stopped.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung milestones: grace * rf^k up to max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        self.rungs: Dict[int, Dict[str, float]] = defaultdict(dict)

    def on_result(self, trial_id: str, result: dict) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        val = float(metric) if self.mode == "max" else -float(metric)
        # Re-check on EVERY report against the highest crossed milestone
        # (ref: async_hyperband.py _Bracket.on_result) — a trial that was
        # first to record at a rung must still be halted once
        # later-arriving peers push the cutoff above it; checking only at
        # the first crossing lets a leading loser run to max_t.
        for ms in reversed(self.milestones):
            if t < ms:
                continue
            rung = self.rungs[ms]
            # record once, at the milestone crossing — overwriting with
            # later (bigger-budget) values would make rung comparisons
            # budget-unfair to trials arriving at the milestone on time
            if trial_id not in rung:
                rung[trial_id] = val
            peers = sorted(rung.values(), reverse=True)
            k = max(1, len(peers) // self.rf)
            cutoff = peers[k - 1]
            if len(peers) >= self.rf and rung[trial_id] < cutoff:
                return STOP
            break  # only the top crossed rung gates continuation
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose best result so far is worse than the median of the
    running-average results of other trials at the same point in time
    (ref: schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, result: dict) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        val = float(metric) if self.mode == "max" else -float(metric)
        self._avgs[trial_id].append(val)
        if t < self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        others = [sum(v) / len(v) for tid, v in self._avgs.items()
                  if tid != trial_id and v]
        if len(others) < self.min_samples - 1:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(self._avgs[trial_id])
        return STOP if best < median else CONTINUE


class HyperBandScheduler:
    """Bracketed successive halving (ref: schedulers/hyperband.py). Trials
    are assigned round-robin to brackets with different grace periods; each
    bracket runs ASHA-style halving at its own milestones."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 81, reduction_factor: int = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        # bracket s runs from grace rf^s with halving every rf
        import math

        s_max = int(math.log(max_t) / math.log(reduction_factor))
        self.brackets = []
        for s in range(s_max + 1):
            self.brackets.append(ASHAScheduler(
                time_attr=time_attr, metric=metric, mode=mode, max_t=max_t,
                grace_period=reduction_factor ** s,
                reduction_factor=reduction_factor))
        self._assignment: Dict[str, int] = {}
        self._next_bracket = 0

    def on_result(self, trial_id: str, result: dict) -> str:
        if trial_id not in self._assignment:
            self._assignment[trial_id] = self._next_bracket
            self._next_bracket = (self._next_bracket + 1) % len(self.brackets)
        b = self.brackets[self._assignment[trial_id]]
        b.metric = b.metric or self.metric
        return b.on_result(trial_id, result)


class PopulationBasedTraining:
    """PBT (ref: schedulers/pbt.py): at every perturbation interval, a trial
    in the bottom quantile clones the checkpoint of a random top-quantile
    trial (exploit) and perturbs its hyperparameters (explore). The
    controller acts on the ("EXPLOIT", source_trial_id, new_config) decision
    by restarting the trial actor from the source checkpoint."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25, seed: int = 0):
        import random as _random

        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.rng = _random.Random(seed)
        self._latest: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = defaultdict(int)
        self._configs: Dict[str, dict] = {}

    def _explore(self, config: dict) -> dict:
        """Perturb mutation params by 0.8x/1.2x or resample (ref:
        pbt.py explore())."""
        from ray_tpu.tune.search import Sampler

        new = dict(config)
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_prob or key not in new:
                if isinstance(spec, Sampler):
                    new[key] = spec.sample(self.rng)
                elif isinstance(spec, list):
                    new[key] = self.rng.choice(spec)
                elif callable(spec):
                    new[key] = spec()
            elif isinstance(new[key], (int, float)) and not isinstance(
                    new[key], bool):
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                new[key] = type(new[key])(new[key] * factor)
            elif isinstance(spec, list):
                # categorical: shift to a neighboring value
                try:
                    i = spec.index(new[key])
                    new[key] = spec[max(0, min(len(spec) - 1,
                                               i + self.rng.choice([-1, 1])))]
                except ValueError:
                    new[key] = self.rng.choice(spec)
        return new

    def on_result(self, trial_id: str, result: dict):
        t = result.get(self.time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        val = float(metric) if self.mode == "max" else -float(metric)
        self._latest[trial_id] = val
        self._configs[trial_id] = result.get("config",
                                             self._configs.get(trial_id, {}))
        if t - self._last_perturb[trial_id] < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        pop = sorted(self._latest.items(), key=lambda kv: -kv[1])
        n = len(pop)
        if n < 4:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        top = [tid for tid, _ in pop[:k]]
        bottom = {tid for tid, _ in pop[-k:]}
        if trial_id in bottom and trial_id not in top:
            source = self.rng.choice(top)
            new_config = self._explore(self._configs.get(source, {}))
            return ("EXPLOIT", source, new_config)
        return CONTINUE
