"""Trial schedulers.

Reference: python/ray/tune/schedulers/ — FIFOScheduler (no-op) and ASHA
(async_hyperband.py): asynchronous successive halving on reported metrics;
a trial that falls below the rung's top-1/reduction_factor quantile at a
milestone is stopped.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung milestones: grace * rf^k up to max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        self.rungs: Dict[int, Dict[str, float]] = defaultdict(dict)

    def on_result(self, trial_id: str, result: dict) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        val = float(metric) if self.mode == "max" else -float(metric)
        decision = CONTINUE
        for ms in self.milestones:
            if t >= ms and trial_id not in self.rungs[ms]:
                self.rungs[ms][trial_id] = val
                peers = sorted(self.rungs[ms].values(), reverse=True)
                k = max(1, len(peers) // self.rf)
                cutoff = peers[k - 1]
                if val < cutoff and len(peers) >= self.rf:
                    decision = STOP
        return decision
