"""Durable workflow DAG execution with per-step checkpoints.

Reference: workflow/api.py + task_executor.py + storage/ — steps are content-
addressed by (workflow_id, step name + arg lineage); results persist via
pickle under the storage dir. Resume = skip steps whose result file exists.
Step bodies execute as ray_tpu tasks.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Dict, List, Optional

import ray_tpu


def _atomic_pickle(path: str, obj: Any) -> None:
    """Write-then-rename so readers never observe a torn checkpoint."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(obj, f)
    os.replace(tmp, path)


def _content_bytes(a: Any) -> bytes:
    """Stable content bytes of a step arg. Plain pickle first; callables
    and anything else plain pickle rejects (lambdas, __main__ closures)
    fall back to cloudpickle, which is what actually ships args to the
    executing task."""
    try:
        return pickle.dumps(a, protocol=4)
    except Exception:
        import cloudpickle

        return cloudpickle.dumps(a, protocol=4)


class StepNode:
    def __init__(self, fn, args, kwargs, name=None, max_retries: int = 3):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or getattr(fn, "__name__", "step")
        self.max_retries = max_retries
        self._key: Optional[str] = None

    def key(self) -> str:
        # Content-address by the *pickled* args, not repr(): numpy reprs
        # elide interior elements, so two different large arrays would
        # collide onto one step key and resume would silently return the
        # wrong cached result (ref checkpoint identity:
        # python/ray/workflow/task_executor.py). Memoized — parents hash
        # their children's keys, so an uncached chain would re-pickle
        # large args once per ancestor.
        if self._key is not None:
            return self._key
        h = hashlib.sha1(self.name.encode())
        for a in self.args:
            h.update(a.key().encode() if isinstance(a, StepNode)
                     else _content_bytes(a))
        for k in sorted(self.kwargs):
            v = self.kwargs[k]
            h.update(k.encode())
            h.update(v.key().encode() if isinstance(v, StepNode)
                     else _content_bytes(v))
        self._key = f"{self.name}-{h.hexdigest()[:16]}"
        return self._key


class _Step:
    def __init__(self, fn, max_retries: int = 3):
        self.fn = fn
        self.max_retries = max_retries

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self.fn, args, kwargs, max_retries=self.max_retries)

    def options(self, max_retries: int = 3) -> "_Step":
        return _Step(self.fn, max_retries)


def step(fn=None, *, max_retries: int = 3):
    """@workflow.step decorator."""
    if fn is not None:
        return _Step(fn, max_retries)
    return lambda f: _Step(f, max_retries)


class EventNode(StepNode):
    """A durable external-event wait (ref: workflow.wait_for_event +
    event_listener.py). Resolution blocks until send_event() delivers a
    payload for (workflow_id, name); the payload checkpoints like any
    step result, so a resumed workflow does NOT re-wait for an event it
    already received."""

    def __init__(self, name: str, timeout: Optional[float] = None,
                 poll_interval: float = 0.05):
        super().__init__(fn=None, args=(), kwargs={}, name=f"event:{name}")
        self.event_name = name
        self.timeout = timeout
        self.poll_interval = poll_interval

    def key(self) -> str:
        return f"event-{self.event_name}"


def wait_for_event(name: str, timeout: Optional[float] = None) -> EventNode:
    """Use as a step argument (or continuation target): the workflow
    parks until `send_event(workflow_id, name, payload)` fires, then the
    payload flows into the dependent step."""
    return EventNode(name, timeout)


def _event_path(storage: str, workflow_id: str, name: str) -> str:
    d = os.path.join(storage, workflow_id, "events")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, name + ".pkl")


def send_event(workflow_id: str, name: str, payload: Any = None, *,
               storage: str) -> None:
    """Deliver an external event (ref: workflow event HTTP endpoint /
    manual event senders). Durable: the payload lands on storage first,
    so a crash between send and receipt re-delivers on resume."""
    _atomic_pickle(_event_path(storage, workflow_id, name), payload)


# ---- workflow queue (ref: max running workflows + QUEUED status) ----------

import threading as _threading

_queue_sem = None
#: thread-local handle to the queue slot the current workflow holds, so
#: event waits can release it while parked
_slot_ctx = _threading.local()


def set_max_running(n: Optional[int]) -> None:
    """Cap concurrently RUNNING workflows started via run_async; excess
    submissions hold in QUEUED status until a slot frees (ref: the
    reference's workflow queue semantics). None lifts the cap."""
    global _queue_sem
    import threading

    _queue_sem = None if n is None else threading.BoundedSemaphore(n)


def _storage_path(storage: str, workflow_id: str, key: str) -> str:
    d = os.path.join(storage, workflow_id, "steps")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, key + ".pkl")


def _write_status(storage: str, workflow_id: str, status: str,
                  error: Optional[str] = None):
    d = os.path.join(storage, workflow_id)
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, "status.tmp")
    with open(tmp, "w") as f:
        f.write(status + ("\n" + error if error else ""))
    os.replace(tmp, os.path.join(d, "status"))


def run(node: StepNode, *, workflow_id: str, storage: str) -> Any:
    """Execute the DAG depth-first; persist each step result; resume skips
    persisted steps (ref: workflow durability contract). A step may
    RETURN a StepNode — a continuation (ref: workflow.continuation) —
    which the executor keeps resolving, enabling dynamic/recursive
    workflows with every intermediate step still checkpointed."""
    memo: Dict[str, Any] = {}
    _write_status(storage, workflow_id, "RUNNING")

    def resolve(n: StepNode) -> Any:
        key = n.key()
        if key in memo:
            return memo[key]
        path = _storage_path(storage, workflow_id, key)
        if os.path.exists(path):
            with open(path, "rb") as f:
                out = pickle.load(f)
            memo[key] = out
            return out
        if isinstance(n, EventNode):
            import time as _time

            ep = _event_path(storage, workflow_id, n.event_name)
            deadline = (None if n.timeout is None
                        else _time.time() + n.timeout)
            # an event wait does no work: give the queue slot back while
            # parked, or a capped queue deadlocks when the event depends
            # on a QUEUED workflow's output
            sem = getattr(_slot_ctx, "sem", None)
            if sem is not None:
                sem.release()
            try:
                while not os.path.exists(ep):
                    if deadline is not None and _time.time() > deadline:
                        raise TimeoutError(
                            f"workflow event {n.event_name!r} not "
                            f"delivered within {n.timeout}s")
                    _time.sleep(n.poll_interval)
            finally:
                if sem is not None:
                    sem.acquire()
            with open(ep, "rb") as f:
                out = pickle.load(f)
            _atomic_pickle(path, out)
            memo[key] = out
            return out
        args = [resolve(a) if isinstance(a, StepNode) else a for a in n.args]
        kwargs = {k: (resolve(v) if isinstance(v, StepNode) else v)
                  for k, v in n.kwargs.items()}
        task = ray_tpu.remote(n.fn).options(max_retries=n.max_retries)
        out = ray_tpu.get(task.remote(*args, **kwargs))
        while isinstance(out, StepNode):   # continuation
            out = resolve(out)
        _atomic_pickle(path, out)
        memo[key] = out
        return out

    try:
        out = resolve(node)
    except BaseException as e:
        _write_status(storage, workflow_id, "FAILED", repr(e))
        raise
    _write_status(storage, workflow_id, "SUCCESSFUL")
    return out


def run_async(node: StepNode, *, workflow_id: str, storage: str):
    """Start the workflow on a daemon thread; returns a concurrent
    Future (ref: workflow/api.py run_async returning an ObjectRef). A
    daemon thread, not an executor: a hung workflow must not block
    interpreter exit via the atexit pool join."""
    import threading
    from concurrent.futures import Future

    fut: Future = Future()
    sem = _queue_sem

    def work():
        try:
            if sem is not None:
                _write_status(storage, workflow_id, "QUEUED")
                sem.acquire()
            # transition to RUNNING only after the slot is held: a QUEUED
            # workflow stays cancel()-able for its whole queue wait
            if not fut.set_running_or_notify_cancel():
                if sem is not None:
                    sem.release()
                _write_status(storage, workflow_id, "CANCELLED")
                return
            try:
                _slot_ctx.sem = sem
                try:
                    fut.set_result(run(node, workflow_id=workflow_id,
                                       storage=storage))
                finally:
                    _slot_ctx.sem = None
            finally:
                if sem is not None:
                    sem.release()
        except BaseException as e:
            fut.set_exception(e)

    threading.Thread(target=work, daemon=True).start()
    return fut


def get_status(workflow_id: str, *, storage: str) -> str:
    """RUNNING / SUCCESSFUL / FAILED / NOT_FOUND (ref: workflow
    get_status)."""
    p = os.path.join(storage, workflow_id, "status")
    if not os.path.exists(p):
        return "NOT_FOUND"
    with open(p) as f:
        return f.read().splitlines()[0]


def list_all(*, storage: str) -> List[tuple]:
    """[(workflow_id, status)] for every workflow under the storage dir
    (ref: workflow.list_all)."""
    if not os.path.isdir(storage):
        return []
    return [(wid, get_status(wid, storage=storage))
            for wid in sorted(os.listdir(storage))
            if os.path.isdir(os.path.join(storage, wid))]


def resume(node: StepNode, *, workflow_id: str, storage: str) -> Any:
    """Re-run a FAILED/interrupted workflow; persisted steps are skipped
    (ref: workflow.resume — the DAG is re-supplied since this build
    stores step results, not pickled DAGs)."""
    return run(node, workflow_id=workflow_id, storage=storage)
