"""ray_tpu.workflow: durable step execution.

Reference: python/ray/workflow/ (api.py:166 run_async, task_executor.py,
storage/) — every step's result is persisted so a crashed workflow resumes
from completed steps instead of recomputing.

    from ray_tpu import workflow

    @workflow.step
    def fetch(): ...

    @workflow.step
    def process(x): ...

    out = workflow.run(process.bind(fetch.bind()),
                       workflow_id="my-flow", storage="/tmp/wf")
    # re-running with the same workflow_id skips completed steps
"""

from ray_tpu.workflow.api import (EventNode, StepNode, get_status,
                                  list_all, resume, run, run_async,
                                  send_event, set_max_running, step,
                                  wait_for_event)

__all__ = ["step", "run", "run_async", "resume", "get_status",
           "list_all", "StepNode", "EventNode", "wait_for_event",
           "send_event", "set_max_running"]
