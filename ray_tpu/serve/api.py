"""serve public API: @deployment, run, shutdown, handles.

Reference: python/ray/serve/api.py:242 (@serve.deployment), :414 (serve.run).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle

CONTROLLER_NAME = "_serve_controller"
_NAMESPACE = "serve"


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Any = None
    autoscaling_config: Optional[dict] = None
    model_autoscaling_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)

    def bind(self, *args, **kwargs) -> "Application":
        d = Deployment(self.func_or_class, self.name, self.num_replicas,
                       self.max_concurrent_queries, self.user_config,
                       self.autoscaling_config, self.model_autoscaling_config,
                       self.ray_actor_options, args, kwargs)
        # Composition (ref: deployment_graph_build.py): nested bound
        # deployments in the init args join this application's deployment
        # list; serve.run turns them into handles at deploy time.
        deps = [d]
        seen = {d.name: d}
        for v in _flatten_values(args, kwargs):
            if isinstance(v, Application):
                for child in v.deployments:
                    prev = seen.get(child.name)
                    if prev is None:
                        seen[child.name] = child
                        deps.append(child)
                    elif prev is not child:
                        raise ValueError(
                            f"two distinct bound deployments share the "
                            f"name {child.name!r}; give one a "
                            ".options(name=...) — merging would route "
                            "both handles to whichever deployed first")
        return Application(deps, d)

    def options(self, **kw) -> "Deployment":
        d = Deployment(self.func_or_class, kw.pop("name", self.name),
                       kw.pop("num_replicas", self.num_replicas),
                       kw.pop("max_concurrent_queries",
                              self.max_concurrent_queries),
                       kw.pop("user_config", self.user_config),
                       kw.pop("autoscaling_config", self.autoscaling_config),
                       kw.pop("model_autoscaling_config",
                              self.model_autoscaling_config),
                       kw.pop("ray_actor_options", self.ray_actor_options))
        if kw:
            raise ValueError(f"unknown deployment options {sorted(kw)}")
        return d


def _flatten_values(args, kwargs):
    out = []

    def scan(v):
        if isinstance(v, (list, tuple)):
            for x in v:
                scan(x)
        elif isinstance(v, dict):
            for x in v.values():
                scan(x)
        else:
            out.append(v)

    for a in args:
        scan(a)
    for a in kwargs.values():
        scan(a)
    return out


@dataclass
class Application:
    deployments: List[Deployment]
    ingress: Deployment

    def __getattr__(self, name: str):
        # graph authoring: `app.method.bind(...)` builds a
        # DeploymentMethodNode (ref: serve deployment graph DAG idiom)
        if (name.startswith("_") and name != "__call__") \
                or name in ("deployments", "ingress"):
            raise AttributeError(name)
        # only resolve methods the bound class actually defines — typos
        # and duck-type probes (hasattr(app, "keys")) must fail here, not
        # at request time inside the DAGDriver
        target = self.ingress.func_or_class
        if not hasattr(target, name):
            raise AttributeError(
                f"{getattr(target, '__name__', target)!r} has no method "
                f"{name!r} to bind")
        from ray_tpu.serve.graph import _GraphMethod

        return _GraphMethod(self, name)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               user_config: Any = None,
               autoscaling_config: Optional[dict] = None,
               model_autoscaling_config: Optional[dict] = None,
               ray_actor_options: Optional[dict] = None):
    def deco(obj):
        return Deployment(obj, name or getattr(obj, "__name__", "deployment"),
                          num_replicas, max_concurrent_queries, user_config,
                          autoscaling_config, model_autoscaling_config,
                          ray_actor_options)

    if _func_or_class is not None:
        return deco(_func_or_class)
    return deco


def _get_or_start_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except ValueError:
        from ray_tpu.serve.controller import ServeController

        try:
            # max_restarts=-1: the controller is a checkpointed state
            # machine (GCS KV) — on death it restarts, restores the
            # deployment table, and re-adopts live named replicas
            # (ref: serve/controller.py:74). max_concurrency sized for
            # one pending long-poll per router/proxy subscriber.
            return ServeController.options(
                name=CONTROLLER_NAME, namespace=_NAMESPACE,
                max_restarts=-1, max_concurrency=64).remote()
        except ValueError:
            return ray_tpu.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)


def _handleize(v):
    """Replace nested bound deployments with runtime handles (ref:
    deployment_graph_build.py — DeploymentNodes become handles in the
    parent's init args)."""
    if isinstance(v, Application):
        return DeploymentHandle(v.ingress.name)
    if isinstance(v, tuple):
        return tuple(_handleize(x) for x in v)
    if isinstance(v, list):
        return [_handleize(x) for x in v]
    if isinstance(v, dict):
        return {k: _handleize(x) for k, x in v.items()}
    return v


def run(app: Application, *, route_prefix: Optional[str] = None,
        _blocking: bool = False) -> DeploymentHandle:
    """Deploy every deployment in the app; returns the ingress handle
    (ref: serve.run api.py:414). route_prefix registers the ingress with
    the HTTP proxy's route table."""
    controller = _get_or_start_controller()
    # children first (bind() appends them after the parent): a parent that
    # warms up through an injected handle in __init__ must find the child's
    # replicas already deployed (ref: topological deploy order in
    # deployment_graph_build.py)
    for d in reversed(app.deployments):
        from ray_tpu.core.runtime import _dumps_function

        blob = _dumps_function(d.func_or_class) \
            if callable(d.func_or_class) else cloudpickle.dumps(d.func_or_class)
        config = {
            "num_replicas": d.num_replicas,
            "max_concurrent_queries": d.max_concurrent_queries,
            "user_config": d.user_config,
            "autoscaling_config": d.autoscaling_config,
            "model_autoscaling_config": d.model_autoscaling_config,
            "ray_actor_options": d.ray_actor_options,
        }
        ray_tpu.get(controller.deploy.remote(
            d.name, blob, _handleize(d.init_args), _handleize(d.init_kwargs),
            config))
    if route_prefix is not None:
        ray_tpu.get(controller.set_route.remote(route_prefix,
                                                app.ingress.name))
    return DeploymentHandle(app.ingress.name)


def start(http_host: str = "127.0.0.1", http_port: int = 0,
          detached: bool = True) -> int:
    """Start the HTTP ingress proxy; returns the bound port (ref:
    serve.start / _private/http_state.py proxy startup)."""
    from ray_tpu.serve.http_proxy import HTTPProxy, PROXY_NAME

    _get_or_start_controller()
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME, namespace=_NAMESPACE)
    except ValueError:
        try:
            proxy = HTTPProxy.options(
                name=PROXY_NAME, namespace=_NAMESPACE,
                max_concurrency=64).remote(http_host, http_port)
        except ValueError:
            proxy = ray_tpu.get_actor(PROXY_NAME, namespace=_NAMESPACE)
    return ray_tpu.get(proxy.ready.remote())


def status() -> dict:
    """Deployment + route table snapshot (ref: serve.status / REST GET)."""
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except ValueError:
        return {"deployments": {}, "routes": {}}
    return {"deployments": ray_tpu.get(controller.list_deployments.remote()),
            "routes": ray_tpu.get(controller.get_routes.remote())}


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def shutdown():
    from ray_tpu.serve.http_proxy import PROXY_NAME

    try:
        proxy = ray_tpu.get_actor(PROXY_NAME, namespace=_NAMESPACE)
        ray_tpu.get(proxy.shutdown.remote())
        ray_tpu.kill(proxy)
    except Exception:
        pass
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except ValueError:
        return
    for name in ray_tpu.get(controller.list_deployments.remote()):
        ray_tpu.get(controller.delete_deployment.remote(name))
    try:
        # stop the control-loop thread before killing the actor: under
        # lane packing the daemon thread would outlive the actor in the
        # shared worker process (see ServeController.shutdown)
        ray_tpu.get(controller.shutdown.remote(), timeout=10)
    except Exception:
        pass  # best effort; kill() still tears down the lane
    ray_tpu.kill(controller)
