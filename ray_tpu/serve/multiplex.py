"""Model multiplexing: many models per replica with LRU load/unload.

Reference: python/ray/serve/multiplex.py — @serve.multiplexed caches up to
max_num_models_per_replica models per replica keyed by the model id that the
caller sets via handle.options(multiplexed_model_id=...); the loader is the
decorated (async) method; serve.get_multiplexed_model_id() reads the id of
the current request.

Beyond the reference shape, this module carries the fleet layer's
per-request context (the tenant tag rides the same contextvar channel as
the model id) and the ModelRegistry: model weights are published ONCE
into the object store and resolved by model id through the GCS KV, so N
replicas on a node share one pinned zero-copy reading of the blob and
cold-model eviction costs nothing the spill tier can't restore.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import itertools
import pickle
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.util import metrics as _um

_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")
_tenant: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_request_tenant", default="")


def get_multiplexed_model_id() -> str:
    """Model id requested by the current call ('' if unset)."""
    return _model_id.get()


def _set_multiplexed_model_id(model_id: str):
    _model_id.set(model_id or "")


def get_request_tenant() -> str:
    """Tenant tag of the current call ('' if unset)."""
    return _tenant.get()


def _set_request_tenant(tenant: str):
    _tenant.set(tenant or "")


_cache_seq = itertools.count()

# Module-held instruments (the metrics registry is weak — instruments
# owned here outlive any one cache). Series split per cache via the tag.
_m_loaded = _um.Gauge(
    "ray_tpu_serve_models_loaded",
    "models resident in a replica's multiplex LRU",
    tag_keys=("cache",))
_m_evictions = _um.Counter(
    "ray_tpu_serve_model_evictions",
    "LRU evictions from replicas' multiplex caches",
    tag_keys=("cache",))


class _ModelCache:
    """Async LRU of loaded models with in-flight load dedup.

    Concurrent get()s of the same cold model share ONE loader call via a
    future; a loader failure wakes every waiter with the exception and
    leaves the id retryable. Eviction (LRU overflow or explicit
    unload()) runs the `unloader` hook so the evicted engine releases
    its page pool / device memory instead of leaking until GC.
    """

    def __init__(self, loader: Callable, max_models: int,
                 unloader: Optional[Callable] = None, name: str = ""):
        self.loader = loader
        self.unloader = unloader
        self.max_models = max_models
        self.cache: OrderedDict = OrderedDict()
        # immutable membership snapshot, republished under the lock on
        # every insert/evict: threads outside the event loop (the
        # replica's decode loop) iterate THIS, never the live
        # OrderedDict — get()'s move_to_end/popitem would otherwise race
        # their iteration with "dict mutated during iteration"
        self._values: tuple = ()
        self.loading: dict = {}   # model_id -> Future (in-flight dedup)
        self.lock = asyncio.Lock()
        self.name = name or f"cache-{next(_cache_seq)}"
        self._tags = {"cache": self.name}
        self.load_count = 0
        self.eviction_count = 0

    def models(self) -> List[str]:
        """Loaded model ids, LRU-first."""
        return list(self.cache.keys())

    def snapshot_items(self) -> List[Tuple[str, Any]]:
        return list(self.cache.items())

    def values_snapshot(self) -> Tuple[Any, ...]:
        """Loaded model objects as an immutable tuple — safe to iterate
        from any thread while the event loop mutates the cache."""
        return self._values

    def __contains__(self, model_id: str) -> bool:
        return model_id in self.cache

    async def get(self, owner, model_id: str):
        async with self.lock:
            if model_id in self.cache:
                self.cache.move_to_end(model_id)
                return self.cache[model_id]
            fut = self.loading.get(model_id)
            if fut is None:
                fut = asyncio.get_event_loop().create_future()
                self.loading[model_id] = fut
                is_loader = True
            else:
                is_loader = False
        if not is_loader:
            # someone else is loading this model; share their result
            return await asyncio.shield(fut)
        try:
            out = self.loader(owner, model_id)
            if asyncio.iscoroutine(out):
                out = await out
        except BaseException as e:
            async with self.lock:
                # clear the in-flight entry AND wake waiters with the
                # failure in one critical section — a waiter arriving
                # between the two would otherwise hang on an orphaned
                # future while the id looks retryable
                self.loading.pop(model_id, None)
                if not fut.done():
                    fut.set_exception(e)
                if fut.done() and not fut.cancelled():
                    fut.exception()   # consume: no "never retrieved"
                                      # warning when no waiter shows up
            raise
        evicted: List[Tuple[str, Any]] = []
        async with self.lock:
            self.cache[model_id] = out
            self.cache.move_to_end(model_id)
            self.loading.pop(model_id, None)
            while len(self.cache) > self.max_models:
                evicted.append(self.cache.popitem(last=False))
            self._values = tuple(self.cache.values())
            self.load_count += 1
            _m_loaded.set(len(self.cache), tags=self._tags)
        for mid, obj in evicted:
            await self._run_unloader(owner, mid, obj)
        if not fut.done():
            fut.set_result(out)
        return out

    async def unload(self, owner, model_id: str) -> bool:
        """Explicitly evict one model (controller scale-down path)."""
        async with self.lock:
            obj = self.cache.pop(model_id, None)
            if obj is not None:
                self._values = tuple(self.cache.values())
                _m_loaded.set(len(self.cache), tags=self._tags)
        if obj is None:
            return False
        await self._run_unloader(owner, model_id, obj)
        return True

    async def _run_unloader(self, owner, model_id: str, obj):
        self.eviction_count += 1
        _m_evictions.inc(tags=self._tags)
        _m_loaded.set(len(self.cache), tags=self._tags)
        if self.unloader is not None:
            try:
                maybe = self.unloader(owner, model_id, obj)
                if asyncio.iscoroutine(maybe):
                    await maybe
            except Exception:
                pass
        # best-effort legacy unload hook (ref: __del__-based unload)
        unload = getattr(obj, "__serve_unload__", None)
        if callable(unload):
            try:
                maybe = unload()
                if asyncio.iscoroutine(maybe):
                    await maybe
            except Exception:
                pass


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3,
                unloader: Optional[Callable] = None):
    """Decorator for the per-replica model loader method. `unloader`,
    if given, is called as unloader(self, model_id, model) when the LRU
    evicts a model."""

    def deco(loader: Callable):
        cache_attr = f"__serve_multiplex_cache_{loader.__name__}"

        @functools.wraps(loader)
        async def wrapper(self, model_id: str):
            cache = getattr(self, cache_attr, None)
            if cache is None:
                cache = _ModelCache(loader, max_num_models_per_replica,
                                    unloader=unloader)
                setattr(self, cache_attr, cache)
            return await cache.get(self, model_id)

        return wrapper

    if func is not None:
        return deco(func)
    return deco


_REGISTRY_NS = "serve_models"


class ModelRegistry:
    """Fleet-wide model-weights registry over the object store.

    publish() puts the weights blob once and maps model_id -> pickled
    ObjectRef in the GCS KV; fetch() on any node resolves the ref — a
    zero-copy local read when a copy is already node-resident, so N
    replicas on one node share a single pinned copy instead of N
    deserialized clones. The publisher keeps its ref alive in
    `_published` (the pin); evicted/spilled copies restore transparently
    through the store's spill tier, which is what makes cold-model LRU
    eviction on replicas free.
    """

    def __init__(self):
        from ray_tpu.core import runtime as _rt
        self._rt = _rt.get_runtime()
        self._published: Dict[str, Any] = {}   # model_id -> ObjectRef pin

    def publish(self, model_id: str, weights: Any):
        """Put `weights` into the object store and register the ref
        under `model_id`. Returns the ObjectRef."""
        import ray_tpu
        ref = ray_tpu.put(weights)
        self._published[model_id] = ref
        self._rt.kv_put(_REGISTRY_NS, model_id.encode(), pickle.dumps(ref))
        return ref

    def contains(self, model_id: str) -> bool:
        return self._rt.kv_get(_REGISTRY_NS, model_id.encode()) is not None

    def ref(self, model_id: str):
        raw = self._rt.kv_get(_REGISTRY_NS, model_id.encode())
        if raw is None:
            raise KeyError(f"model {model_id!r} is not published")
        return pickle.loads(raw)

    def fetch(self, model_id: str, timeout: Optional[float] = 30.0) -> Any:
        """Resolve the published weights for `model_id` (KeyError if the
        id was never published)."""
        import ray_tpu
        return ray_tpu.get(self.ref(model_id), timeout=timeout)
