"""Model multiplexing: many models per replica with LRU load/unload.

Reference: python/ray/serve/multiplex.py — @serve.multiplexed caches up to
max_num_models_per_replica models per replica keyed by the model id that the
caller sets via handle.options(multiplexed_model_id=...); the loader is the
decorated (async) method; serve.get_multiplexed_model_id() reads the id of
the current request.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
from collections import OrderedDict
from typing import Callable, Optional

_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Model id requested by the current call ('' if unset)."""
    return _model_id.get()


def _set_multiplexed_model_id(model_id: str):
    _model_id.set(model_id or "")


class _ModelCache:
    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.cache: OrderedDict = OrderedDict()
        self.loading: dict = {}   # model_id -> Future (in-flight dedup)
        self.lock = asyncio.Lock()

    async def get(self, owner, model_id: str):
        async with self.lock:
            if model_id in self.cache:
                self.cache.move_to_end(model_id)
                return self.cache[model_id]
            fut = self.loading.get(model_id)
            if fut is None:
                fut = asyncio.get_event_loop().create_future()
                self.loading[model_id] = fut
                is_loader = True
            else:
                is_loader = False
        if not is_loader:
            # someone else is loading this model; share their result
            return await asyncio.shield(fut)
        try:
            out = self.loader(owner, model_id)
            if asyncio.iscoroutine(out):
                out = await out
        except BaseException as e:
            async with self.lock:
                self.loading.pop(model_id, None)
            if not fut.done():
                fut.set_exception(e)
            raise
        async with self.lock:
            self.cache[model_id] = out
            self.cache.move_to_end(model_id)
            self.loading.pop(model_id, None)
            while len(self.cache) > self.max_models:
                _, evicted = self.cache.popitem(last=False)
                # best-effort unload hook (ref: __del__-based unload)
                unload = getattr(evicted, "__serve_unload__", None)
                if callable(unload):
                    try:
                        maybe = unload()
                        if asyncio.iscoroutine(maybe):
                            await maybe
                    except Exception:
                        pass
        if not fut.done():
            fut.set_result(out)
        return out


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for the per-replica model loader method."""

    def deco(loader: Callable):
        cache_attr = f"__serve_multiplex_cache_{loader.__name__}"

        @functools.wraps(loader)
        async def wrapper(self, model_id: str):
            cache = getattr(self, cache_attr, None)
            if cache is None:
                cache = _ModelCache(loader, max_num_models_per_replica)
                setattr(self, cache_attr, cache)
            return await cache.get(self, model_id)

        return wrapper

    if func is not None:
        return deco(func)
    return deco
