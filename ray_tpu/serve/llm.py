"""Continuous-batching LLM engine + serve deployment.

The vLLM-capability analog for TPU (BASELINE.md config 4: continuous-batched
llama serving; SURVEY.md §7.9). The reference has no native LLM engine — its
serve layer delegates to user code. TPU-first design constraints drive the
shape of this engine (SURVEY.md §7 hard parts: "static-shape XLA vs dynamic
batch composition; bucketed compilation"):

- a fixed pool of decode SLOTS: the decode step is one jitted program of
  static shape [max_slots] regardless of how many requests are active
  (inactive rows are masked) — no recompilation as requests come and go.
- bucketed prefill: prompts are right-padded to a power-of-two bucket, so
  XLA compiles one prefill program per bucket size; per-row true lengths
  keep attention exact (pad slots are never attended).
- admission: new requests prefill into free slots between decode steps —
  continuous batching, not static batches.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger("ray_tpu.serve.llm")


class LLMQueueFull(Exception):
    """Raised by submit() when the engine's admission queue is at
    max_queue_depth — the serve layer maps it to HTTP 429 so load sheds
    at the proxy instead of building unbounded queue-wait (VERDICT r2
    weak #3: 'no backpressure/429 path')."""


@dataclass
class _Request:
    req_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    slot: int = -1
    generated: List[int] = field(default_factory=list)
    #: tokens present in BOTH prompt and generated after a recompute-
    #: preemption folded generated tokens into the resume prompt; real
    #: sequence length = len(prompt) + len(generated) - overlap
    overlap: int = 0
    error: Optional[str] = None
    done_event: threading.Event = field(default_factory=threading.Event)
    # pulsed whenever generated grows (token-streaming consumers wait on it)
    progress: threading.Event = field(default_factory=threading.Event)
    submit_time: float = field(default_factory=time.time)
    first_token_time: Optional[float] = None


class LLMEngine:
    """Synchronous engine core; drive with step(). Thread-safe submit."""

    def __init__(self, cfg=None, params=None, *, preset: str = "tiny",
                 max_slots: int = 8, max_seq_len: Optional[int] = None,
                 eos_token: int = -1, seed: int = 0, mesh=None, rules=None,
                 kv_layout: str = "contiguous", page_size: int = 64,
                 num_pages: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 prefix_caching: bool = True,
                 prefix_cache_max_tail: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 quantize: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama

        self._jax = jax
        self._jnp = jnp
        self._llama = llama
        if cfg is None:
            cfg = llama.PRESETS[preset]
            if jax.default_backend() != "tpu":
                cfg = cfg.replace(dtype=jnp.float32)
        self.max_seq = max_seq_len or cfg.max_seq_len
        if self.max_seq > cfg.max_seq_len:
            # decode paths size their RoPE tables from cfg.max_seq_len;
            # serving past it would CLAMP the position index (jax OOB
            # gather) — position>=cfg.max_seq_len tokens would all get
            # the last row's rotation, silently diverging from prefill
            # (whose tables are sized to the actual prompt). RoPE is
            # computed, not learned, so extending the cfg is exact.
            cfg = cfg.replace(max_seq_len=self.max_seq)
        self.cfg = cfg
        self.max_slots = max_slots
        self.eos = eos_token
        self.max_queue_depth = max_queue_depth
        if quantize is not None and quantize != "int8":
            raise ValueError(f"quantize must be 'int8', got {quantize!r}")
        quantized = False
        if params is None:
            # Serving holds no optimizer/master weights: init straight in
            # the compute dtype (bf16 on TPU). f32 masters would DOUBLE
            # weight HBM — at 2.7B that alone is 10.8 of the chip's
            # 16 GB and the engine OOMs before its first admit.
            icfg = cfg.replace(param_dtype=cfg.dtype)
            cpu_dev = None
            if quantize == "int8" and mesh is None \
                    and jax.default_backend() != "cpu":
                try:
                    cpu_dev = jax.devices("cpu")[0]
                except RuntimeError:
                    cpu_dev = None   # no host backend: quantize on-chip
            if cpu_dev is not None:
                # init + quantize on HOST, ship only the int8 tree: doing
                # both on-chip transiently holds bf16 AND int8 copies
                # (7B: ~20 GB peak — past the chip) before the bf16 side
                # is freed
                with jax.default_device(cpu_dev):
                    params = llama.init_params(jax.random.PRNGKey(seed),
                                               icfg)
                    params = llama.quantize_params_int8(params)
                params = jax.device_put(params, jax.devices()[0])
                quantized = True
            else:
                params = llama.init_params(jax.random.PRNGKey(seed), icfg)
        if mesh is not None and rules is not None:
            from ray_tpu.parallel.sharding import shard_params

            params = shard_params(mesh, params, llama.param_specs(cfg), rules)
        if quantize == "int8" and not quantized:
            # weight-only int8: HBM at rest halves vs bf16 (7B: ~6.8 GB);
            # weights dequantize inside the consuming dots. Idempotent:
            # already-quantized caller trees pass through unchanged.
            # (After sharding: the quantized tree's {"q8","s8"} leaves no
            # longer match param_specs.)
            params = llama.quantize_params_int8(params)
        self.quantize = quantize
        self.params = params
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(f"kv_layout must be 'contiguous' or 'paged', "
                             f"got {kv_layout!r}")
        if kv_layout == "paged" and cfg.sliding_window is not None:
            # fail HERE, not inside the server's background decode thread
            # (where the ValueError would kill the loop and hang clients)
            raise ValueError(
                "kv_layout='paged' does not support sliding_window "
                "configs; use the contiguous layout for windowed models")
        self.kv_layout = kv_layout
        if kv_layout == "paged":
            from ray_tpu.serve.paged_kv import PagePool

            maxP = -(-self.max_seq // page_size)
            # default pool = the HBM a contiguous cache would commit
            # (+ trash page); the paged win is packing MORE slots into it
            num_pages = num_pages or max_slots * maxP + 1
            self.kp, self.vp = llama.init_paged_cache(cfg, num_pages,
                                                      page_size)

            def _nb(x):
                try:
                    return int(x.nbytes)
                except Exception:
                    try:
                        return sum(int(a.nbytes) for a in x)
                    except Exception:
                        return 0

            # per-page device bytes (K+V across layers) so the pool can
            # report occupied-page bytes to the memory plane
            page_nbytes = (_nb(self.kp) + _nb(self.vp)) // num_pages
            self.pool = PagePool(num_pages, page_size, max_slots, maxP,
                                 page_nbytes=page_nbytes)
            # automatic prefix caching (ref: vLLM APC): share full
            # prompt pages by content hash; a hit skips that prefix's
            # prefill compute AND its page memory, and ONE chunked
            # tail-prefill call (O(T x total) attention against the
            # cached pages) finishes admission. The tail cap bounds
            # that call's cost; a mostly-unmatched prompt takes the
            # plain batched prefill instead.
            self.prefix_caching = bool(prefix_caching)
            self.prefix_cache_max_tail = (
                prefix_cache_max_tail if prefix_cache_max_tail is not None
                else 4 * page_size)
            self._len_host = np.zeros((max_slots,), np.int64)
            self._pt_dev = jnp.asarray(self.pool.table)
            self._len_dev = jnp.zeros((max_slots,), jnp.int32)
            self._table_dirty = False
            self.cache = None
        else:
            self.cache = llama.init_cache(cfg, max_slots,
                                          max_seq=self.max_seq)
        self.slots: List[Optional[_Request]] = [None] * max_slots
        self.lock = threading.Lock()
        self.pending: List[_Request] = []
        # requests that own a slot but are still mid-prefill: each _admit
        # round advances them by one bounded chunk (ref: vLLM chunked
        # prefill — prefill work is scheduled in chunks between decode
        # steps instead of monopolizing a round). They are masked OUT of
        # decode until their tail completes.
        self._prefilling: List[_Request] = []
        #: chunk size for the chunked-prefill path. None ⇒ plain prompts
        #: prefill in one call (current perf behavior) and only prefix-
        #: cache tails are chunked (at prefix_cache_max_tail tokens per
        #: round). Set it to bound per-round prefill latency for BOTH
        #: kv layouts.
        self.prefill_chunk = prefill_chunk
        self._next_id = 0
        # device-resident decode state: last tokens, active mask, temps,
        # PRNG key. Uploaded only when slot membership changes — per-block
        # host->device transfers each cost a transport round trip
        self._last = jnp.zeros((max_slots, 1), jnp.int32)
        self._active_dev = jnp.zeros((max_slots,), jnp.int32)
        self._temps_dev = jnp.zeros((max_slots,), jnp.float32)
        self._key = jax.random.PRNGKey(seed ^ 0x5eed)
        self._masks_dirty = True

        if kv_layout == "paged":
            self._decode_paged = jax.jit(
                lambda p, t, kp, vp, pt, ln, a: llama.decode_step_paged(
                    p, t, kp, vp, pt, ln, cfg, active=a),
                donate_argnums=(2, 3))
            self._scatter = jax.jit(
                lambda kp, vp, ks, vs, pt, sl, ln: llama.
                scatter_prefill_pages(kp, vp, ks, vs, pt, sl, ln,
                                      page_size),
                donate_argnums=(0, 1))
            # chunked tail prefill against cached prefix pages: ONE
            # device call finishes a prefix-hit admission (token-by-token
            # draining costs a transport round trip per tail token)
            self._prefill_tail = jax.jit(
                lambda p, t, tl, pl, pt, kp, vp: llama.prefill_paged_tail(
                    p, t, tl, pl, pt, kp, vp, cfg),
                donate_argnums=(5, 6))

            def _multi_paged(params, last, kp, vp, pt, ln, active, temps,
                             key, n):
                def body(carry, _):
                    last, kp, vp, ln, key = carry
                    logits, kp, vp, ln = llama.decode_step_paged(
                        params, last, kp, vp, pt, ln, cfg, active=active)
                    key, sub = jax.random.split(key)
                    greedy = jnp.argmax(logits, axis=-1)
                    sampled = jax.random.categorical(
                        sub, logits / jnp.maximum(temps, 1e-4)[:, None],
                        axis=-1)
                    tok = jnp.where(temps <= 0.0, greedy, sampled)
                    return ((tok[:, None].astype(jnp.int32), kp, vp, ln,
                             key), tok)

                (last, kp, vp, ln, key), toks = jax.lax.scan(
                    body, (last, kp, vp, ln, key), None, length=n)
                return toks, last, kp, vp, ln, key

            self._decode_n_paged = jax.jit(_multi_paged, static_argnames="n",
                                           donate_argnums=(2, 3))
        else:
            self._decode = jax.jit(
                lambda p, t, c, a: llama.decode_step(p, t, c, cfg, active=a),
                donate_argnums=(2,))  # cache aliases in place across calls
            # chunked-prefill twin for the contiguous layout: writes a
            # bounded token chunk into slot rows at their current fill
            self._prefill_tail_contig = jax.jit(
                lambda p, t, tl, pl, sl, c: llama.prefill_tail_contiguous(
                    p, t, tl, pl, c, sl, cfg),
                donate_argnums=(5,))
        self._prefill = jax.jit(
            lambda p, t, l: llama.prefill(p, t, l, cfg))  # noqa: E741

        def _multi(params, last, cache, active, temps, key, n):
            # n fused decode steps with ON-DEVICE sampling: one host
            # round-trip per n tokens instead of per token (the per-step
            # logits fetch dominates decode latency on any transport)
            def body(carry, _):
                last, cache, key = carry
                logits, cache = llama.decode_step(params, last, cache, cfg,
                                                  active=active)
                key, sub = jax.random.split(key)
                greedy = jnp.argmax(logits, axis=-1)
                sampled = jax.random.categorical(
                    sub, logits / jnp.maximum(temps, 1e-4)[:, None], axis=-1)
                tok = jnp.where(temps <= 0.0, greedy, sampled)
                return (tok[:, None].astype(jnp.int32), cache, key), tok

            (last, cache, key), toks = jax.lax.scan(
                body, (last, cache, key), None, length=n)
            return toks, last, cache, key  # toks: [n, slots]

        self._decode_n = jax.jit(_multi, static_argnames="n",
                                 donate_argnums=(2,))

        self.metrics = {"requests": 0, "tokens_generated": 0,
                        "ttft_sum": 0.0, "ttft_count": 0}
        # Cluster-visible instruments (util.metrics -> batched telemetry
        # reports), replica-tagged so the future serve router can read
        # per-replica admission cost and TTFT percentiles from the GCS.
        # The plain dict above stays the local stats() view.
        from ray_tpu.util import metrics as _um
        try:
            import ray_tpu
            replica = (ray_tpu.get_runtime_context().get_actor_id()
                       or "driver")
        except Exception:
            replica = "local"
        tag = {"replica": str(replica)[:16]}
        self._m_ttft = _um.Histogram(
            "ray_tpu_serve_ttft_s", "time to first token per request",
            boundaries=[0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30],
            tag_keys=("replica",)).set_default_tags(tag)
        self._m_admit = _um.Counter(
            "ray_tpu_serve_admit_s", "seconds spent in request admission",
            tag_keys=("replica",)).set_default_tags(tag)
        self._m_decode_block = _um.Histogram(
            "ray_tpu_serve_decode_block_s",
            "fused decode-block wall seconds",
            boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5],
            tag_keys=("replica",)).set_default_tags(tag)
        self._m_tokens = _um.Counter(
            "ray_tpu_serve_tokens_generated", "generated tokens",
            tag_keys=("replica",)).set_default_tags(tag)

    def _record_first_token(self, r, now: float) -> None:
        """Client-visible TTFT, once per request (re-admission after a
        recompute-preemption must not reset it or double-count)."""
        r.first_token_time = now
        ttft = now - r.submit_time
        self.metrics["ttft_sum"] += ttft
        self.metrics["ttft_count"] += 1
        self._m_ttft.observe(ttft)

    # ---- submission --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               temperature: float = 0.0) -> _Request:
        with self.lock:
            if (self.max_queue_depth is not None
                    and len(self.pending) >= self.max_queue_depth):
                self.metrics["rejected"] = \
                    self.metrics.get("rejected", 0) + 1
                raise LLMQueueFull(
                    f"admission queue at max_queue_depth="
                    f"{self.max_queue_depth}; retry later")
            req = _Request(self._next_id, list(prompt), max_new_tokens,
                           temperature)
            self._next_id += 1
            self.pending.append(req)
            self.metrics["requests"] += 1
        return req

    def has_work(self) -> bool:
        with self.lock:
            return bool(self.pending) or any(s is not None for s in self.slots)

    # ---- engine step -------------------------------------------------------

    def _bucket(self, n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return min(b, self.max_seq)

    def _chunk_size(self) -> int:
        """Tokens of prefill per admission round for the chunked path."""
        if self.prefill_chunk:
            return self.prefill_chunk
        if self.kv_layout == "paged":
            return self.prefix_cache_max_tail
        return 512

    def _admit(self):
        import jax.numpy as jnp

        chunk = self._chunk_size()
        with self.lock:
            free = [i for i, s in enumerate(self.slots) if s is None]
            chunked_new = []
            admit = []
            if self.kv_layout == "paged":
                # FIFO admission gated on BOTH a free slot and enough
                # free pages for the prompt — head-of-line blocks
                # rather than starving long prompts
                for r in list(self.pending):
                    if not free:
                        break
                    plen = min(len(r.prompt), self.max_seq - 1)
                    # a prompt that can NEVER fit must fail now, or it
                    # head-of-line blocks the queue forever
                    if self.pool.pages_for(plen) > min(
                            self.pool.max_pages_per_slot,
                            self.pool.num_pages - 1):
                        self.pending.remove(r)
                        r.error = (f"prompt of {plen} tokens exceeds the "
                                   f"KV page pool capacity")
                        r.done_event.set()
                        r.progress.set()
                        continue
                    if self._try_admit_cached(r, free, plen):
                        chunked_new.append(r)
                        self.pending.remove(r)
                        continue
                    slot = free[0]
                    if not self.pool.grow(slot, plen):
                        break
                    free.pop(0)
                    self._assign_slot(r, slot, plen, chunk, chunked_new,
                                      admit)
                    self.pending.remove(r)
            else:
                for r in list(self.pending):
                    if not free:
                        break
                    plen = min(len(r.prompt), self.max_seq - 1)
                    self._assign_slot(r, free.pop(0), plen, chunk,
                                      chunked_new, admit)
                    self.pending.remove(r)
            self._prefilling.extend(chunked_new)
        # advance every mid-prefill request (fresh prefix hits included)
        # by one bounded chunk — one device call for the whole set
        self._prefill_round(chunk)
        if not admit:
            return
        P = self._bucket(max(len(r.prompt) for r in admit))
        toks = np.zeros((len(admit), P), np.int32)
        lens = np.zeros((len(admit),), np.int32)
        for i, r in enumerate(admit):
            p = r.prompt[-P:]
            toks[i, :len(p)] = p
            lens[i] = len(p)
        logits, ks, vs = self._prefill(self.params, jnp.asarray(toks),
                                       jnp.asarray(lens))
        if self.kv_layout == "paged":
            slots = jnp.asarray([r.slot for r in admit])
            self._pt_dev = jnp.asarray(self.pool.table)
            self.kp, self.vp = self._scatter(
                self.kp, self.vp, ks, vs, self._pt_dev, slots,
                jnp.asarray(lens))
            for i, r in enumerate(admit):
                self._len_host[r.slot] = int(lens[i])
                r._filled = int(lens[i])
            self._len_dev = jnp.asarray(self._len_host.astype(np.int32))
            self._table_dirty = False
        else:
            # scatter new kv into cache slots + set lengths
            slots = jnp.asarray([r.slot for r in admit])
            k = self.cache.k.at[:, slots, :P].set(
                ks.astype(self.cache.k.dtype))
            v = self.cache.v.at[:, slots, :P].set(
                vs.astype(self.cache.v.dtype))
            length = self.cache.length.at[slots].set(jnp.asarray(lens))
            for i, r in enumerate(admit):
                r._filled = int(lens[i])
            from ray_tpu.models.llama import KVCache

            self.cache = KVCache(k, v, length)
        self._masks_dirty = True
        self._emit_first_tokens(list(enumerate(admit)), logits, len(admit))

    def _assign_slot(self, r, slot: int, plen: int, chunk: int,
                     chunked_new: list, admit: list):
        """Bind a request to its slot (caller holds self.lock), routing
        long prompts to the chunked-prefill path when enabled."""
        r.slot = slot
        self.slots[slot] = r
        if self.prefill_chunk and plen > chunk:
            # long prompt: bounded chunks across admission rounds
            # instead of one monopolizing prefill
            r._tail = list(r.prompt[-plen:])
            r._filled = 0
            if self.kv_layout == "paged":
                self._len_host[slot] = 0
            chunked_new.append(r)
        else:
            admit.append(r)

    def _emit_first_tokens(self, pairs, logits, nb: int):
        """Shared completion path for every prefill flavor (plain,
        prefix-hit, chunked): sample each finished row's first token
        from its logits row, record TTFT, register prompt pages for
        prefix caching, and finish/notify. pairs = [(logits_row,
        request)]; nb = the logits batch size (pad rows get temp 0)."""
        import jax.numpy as jnp

        if not pairs:
            return
        temps = [0.0] * nb
        for i, r in pairs:
            temps[i] = r.temperature
        first = np.asarray(self._sample(logits, temps))
        upd = jnp.asarray([r.slot for _, r in pairs])
        self._last = self._last.at[upd, 0].set(jnp.asarray(
            np.asarray([int(first[i]) for i, _ in pairs], np.int32)))
        now = time.time()
        for i, r in pairs:
            r.generated.append(int(first[i]))
            if r.first_token_time is None:
                self._record_first_token(r, now)
            self.metrics["tokens_generated"] += 1
            self._m_tokens.inc()
            if (self.kv_layout == "paged" and self.prefix_caching
                    and r._filled < self.max_seq):
                from ray_tpu.serve.paged_kv import page_chain_hashes

                # register this prompt's FULL pages for later hits
                # (prefill wrote their KV; they stay read-only — decode
                # appends past the fill). Prompts truncated to the FULL
                # max_seq window are skipped: the lookup side views the
                # last max_seq-1 tokens, so the page boundaries would
                # shift by one token and the pages' KV wouldn't
                # correspond to any lookup view.
                self.pool.register(r.slot, page_chain_hashes(
                    list(r.prompt)[-r._filled:], self.pool.page_size))
            self._maybe_finish(r)
            r.progress.set()

    def _prefill_round(self, chunk: int):
        """One bounded prefill chunk for every mid-prefill request, in
        ONE device call (ref: vLLM chunked prefill scheduling — prefill
        advances between decode steps instead of monopolizing a round).
        Requests whose tail completes sample their first token here and
        join the next decode step; the rest stay masked out of decode
        and continue next round."""
        import jax.numpy as jnp

        with self.lock:
            rows = list(self._prefilling)
        if not rows:
            return
        takes = [min(len(r._tail), chunk) for r in rows]
        Tb = self._bucket(max(takes))
        n = len(rows)
        if self.kv_layout == "paged":
            # pad the BATCH dim to a pow2 bucket: every distinct (n, T)
            # shape is its own XLA program. Pad rows have tail_len 0, so
            # their writes land in the trash page.
            nb = 1
            while nb < n:
                nb *= 2
        else:
            # contiguous has no trash row a pad entry could safely
            # target, so the batch dim stays exact (bounded by
            # max_slots distinct programs)
            nb = n
        toks = np.zeros((nb, Tb), np.int32)
        tl = np.zeros((nb,), np.int32)
        pl = np.zeros((nb,), np.int32)
        for i, r in enumerate(rows):
            t = r._tail[:takes[i]]
            toks[i, :len(t)] = t
            tl[i] = len(t)
            pl[i] = r._filled
        if self.kv_layout == "paged":
            tab = np.zeros((nb, self.pool.table.shape[1]), np.int32)
            tab[:n] = self.pool.table[[r.slot for r in rows]]
            logits, self.kp, self.vp = self._prefill_tail(
                self.params, jnp.asarray(toks), jnp.asarray(tl),
                jnp.asarray(pl), jnp.asarray(tab), self.kp, self.vp)
        else:
            slot_ids = jnp.asarray([r.slot for r in rows], jnp.int32)
            logits, self.cache = self._prefill_tail_contig(
                self.params, jnp.asarray(toks), jnp.asarray(tl),
                jnp.asarray(pl), slot_ids, self.cache)
        finished = []
        with self.lock:
            for i, r in enumerate(rows):
                r._filled += takes[i]
                r._tail = r._tail[takes[i]:]
                if self.kv_layout == "paged":
                    self._len_host[r.slot] = r._filled
                if not r._tail:
                    finished.append((i, r))
                    self._prefilling.remove(r)
            self._masks_dirty = True
            if self.kv_layout == "paged":
                self._table_dirty = True
        self._emit_first_tokens(finished, logits, nb)

    def _try_admit_cached(self, r, free: List[int], plen: int) -> bool:
        """Prefix-cache admission (caller holds self.lock): if the
        prompt's leading FULL pages are cached, adopt them — no prefill
        compute, no new pages for the prefix. The unmatched tail is
        finished by the chunked-prefill rounds (at most
        prefix_cache_max_tail — or prefill_chunk — tokens per round), so
        a long tail no longer forces a full re-prefill of the matched
        prefix. Returns False to fall back to the full prefill."""
        if not self.prefix_caching:
            return False
        from ray_tpu.serve.paged_kv import page_chain_hashes

        ptoks = list(r.prompt[-plen:])   # view matching registration
        # memoized: a head-of-line-blocked request would otherwise
        # re-hash its whole prompt once per decode step until admission
        # (preemption rebuilds the prompt and clears the memo)
        hashes = getattr(r, "_page_hashes", None)
        if hashes is None:
            hashes = page_chain_hashes(ptoks, self.pool.page_size)
            if len(hashes) * self.pool.page_size >= plen:
                hashes = hashes[:-1]  # keep >=1 tail token as decode input
            r._page_hashes = hashes
        if not hashes:
            return False
        pages = self.pool.match_prefix(hashes)
        if not pages:
            return False
        matched = len(pages) * self.pool.page_size
        slot = free[0]
        self.pool.adopt(slot, pages)
        if not self.pool.grow(slot, plen):   # room for the tail's KV
            self.pool.release(slot)          # rollback: drops the refs
            return False
        free.pop(0)
        r.slot = slot
        self.slots[slot] = r
        self._len_host[slot] = matched       # tail-prefill advances it
        r._tail = ptoks[matched:]
        r._filled = matched
        self.metrics["prefix_hits"] = \
            self.metrics.get("prefix_hits", 0) + 1
        self.metrics["prefix_hit_tokens"] = \
            self.metrics.get("prefix_hit_tokens", 0) + matched
        return True

    def _sample(self, logits, temps):
        import jax

        jnp = self._jnp
        logits = jnp.asarray(logits)
        greedy = jnp.argmax(logits, axis=-1)
        if all(t == 0.0 for t in temps):
            return greedy
        key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        t = jnp.asarray([max(tt, 1e-4) for tt in temps])[:, None]
        sampled = jax.random.categorical(key, logits / t, axis=-1)
        use_greedy = jnp.asarray([tt == 0.0 for tt in temps])
        return jnp.where(use_greedy, greedy, sampled)

    def _seq_len(self, r: _Request) -> int:
        return len(r.prompt) + len(r.generated) - r.overlap

    @staticmethod
    def _decode_ready(r: Optional[_Request]) -> bool:
        """A slot participates in decode only once its prefill is
        complete — mid-chunked-prefill rows are masked out."""
        return r is not None and not getattr(r, "_tail", None)

    def _maybe_finish(self, r: _Request):
        if (len(r.generated) >= r.max_new_tokens
                or (self.eos >= 0 and r.generated
                    and r.generated[-1] == self.eos)
                or self._seq_len(r) >= self.max_seq - 1):
            with self.lock:
                if r.slot >= 0:
                    if self.kv_layout == "paged":
                        self.pool.release(r.slot)
                        self._len_host[r.slot] = 0
                        self._table_dirty = True
                    self.slots[r.slot] = None
                    r.slot = -1
                    self._masks_dirty = True
            r.done_event.set()
            r.progress.set()

    def _preempt_one(self) -> bool:
        """Paged pools exhausted mid-decode: evict the most recently
        admitted request (vLLM's recompute-preemption policy) — its
        pages free up, it rejoins the FRONT of the queue with
        prompt+generated as the new prompt, and prefill recomputes its
        KV when pages are available again."""
        with self.lock:
            active = [r for r in self.slots if r is not None]
            if len(active) <= 1:
                return False
            victim = max(active, key=lambda r: r.req_id)
            self.pool.release(victim.slot)
            self._len_host[victim.slot] = 0
            self.slots[victim.slot] = None
            victim.slot = -1
            # resume prompt = everything decoded so far; `overlap` keeps
            # sequence-length accounting from double-counting the tokens
            # now present in both prompt and generated (repeat-preempt
            # safe: only the not-yet-folded tail is appended)
            victim.prompt = list(victim.prompt) + \
                list(victim.generated[victim.overlap:])
            victim.overlap = len(victim.generated)
            # the resume prompt changed, so its page hashes did too
            if hasattr(victim, "_page_hashes"):
                del victim._page_hashes
            # a mid-prefill victim restarts admission from scratch:
            # its chunk progress lived in the released pages
            if getattr(victim, "_tail", None):
                victim._tail = None
                try:
                    self._prefilling.remove(victim)
                except ValueError:
                    pass
            self.pending.insert(0, victim)
            self._table_dirty = True
            self._masks_dirty = True
            self.metrics["preemptions"] = \
                self.metrics.get("preemptions", 0) + 1
        return True

    def _ensure_paged_capacity(self, n: int) -> int:
        """Grow every active slot to hold n more tokens, preempting if
        the pool runs dry. Returns the usable n (0 if nothing active)."""
        def pages_needed(n_try: int) -> int:
            total = 0
            for r in active:
                if r.slot < 0:
                    continue
                need_tok = min(int(self._len_host[r.slot]) + n_try,
                               self.max_seq)
                need_pages = self.pool.pages_for(need_tok)
                total += max(need_pages - len(self.pool.owned[r.slot]), 0)
            return total

        def try_grow(n_try: int) -> bool:
            # precheck against the pool so a doomed attempt allocates
            # NOTHING: partial grants skew the halved retry's
            # redistribution and can force an avoidable
            # recompute-preemption right after pages were granted.
            # available_pages counts refcount-0 cached pages too —
            # grow() reclaims them on demand.
            if pages_needed(n_try) > self.pool.available_pages:
                return False
            ver_before = self.pool.table_version
            ok = True
            for r in active:
                if r.slot < 0:
                    continue
                need = int(self._len_host[r.slot]) + n_try
                if not self.pool.grow(r.slot, min(need, self.max_seq)):
                    ok = False
                    break
            if self.pool.table_version != ver_before:
                # table mutated: device copy is stale. (used_pages can't
                # detect this — growth served from cache reclaim is a
                # net-zero page-count change.)
                self._table_dirty = True
            return ok

        while True:
            with self.lock:
                active = [r for r in self.slots if r is not None]
            if not active:
                return 0
            # prefer a smaller block over evicting someone: preemption
            # costs a full prefill recompute, a short block costs only
            # extra host syncs
            n_try = n
            while n_try >= 1:
                if try_grow(n_try):
                    return n_try
                n_try //= 2
            if not self._preempt_one():
                # lone request can't grow: cap the block at the tokens
                # its current pages still hold (0 -> caller finishes it)
                slot = active[0].slot
                cap = len(self.pool.owned[slot]) * self.pool.page_size
                return max(min(n, cap - int(self._len_host[slot])), 0)

    def _sync_paged_device_state(self, active_mask, temps=None):
        """Upload ONLY what went stale: every host->device transfer costs
        a transport round-trip, and the steady decode loop should cost
        zero of them (lengths advance on device; the table/masks change
        only on admit/finish/preempt/page-growth)."""
        import jax.numpy as jnp

        if self._table_dirty:
            self._pt_dev = jnp.asarray(self.pool.table)
            self._table_dirty = False
        if self._masks_dirty:
            self._active_dev = jnp.asarray(active_mask)
            if temps is not None:
                self._temps_dev = jnp.asarray(temps)
            self._len_dev = jnp.asarray(self._len_host.astype(np.int32))
            self._masks_dirty = False
        return self._active_dev

    def step(self) -> int:
        """Admit + one decode step for all active slots. Returns number of
        active requests after the step."""
        import jax.numpy as jnp

        self._admit()
        with self.lock:
            active_reqs = [r for r in self.slots if self._decode_ready(r)]
            active_mask = np.array(
                [1 if self._decode_ready(s) else 0 for s in self.slots],
                np.int32)
            occupied = sum(1 for s in self.slots if s is not None)
        if not active_reqs:
            # mid-prefill slots may still be occupied: report them so
            # callers keep driving the engine
            return occupied
        if self.kv_layout == "paged":
            if self._ensure_paged_capacity(1) < 1:
                for r in list(active_reqs):
                    # page-capped truncation is an ERROR the client must
                    # see — a silent early finish is indistinguishable
                    # from a complete generation
                    r.max_new_tokens = len(r.generated)
                    r.error = ("generation truncated: KV page pool "
                               f"exhausted after {len(r.generated)} tokens")
                    self._maybe_finish(r)
                return 0
            # capacity growth may have preempted a slot — re-snapshot
            with self.lock:
                active_reqs = [r for r in self.slots
                               if self._decode_ready(r)]
                active_mask = np.array(
                    [1 if self._decode_ready(s) else 0
                     for s in self.slots], np.int32)
                np_temps = np.zeros((self.max_slots,), np.float32)
                for r in active_reqs:
                    np_temps[r.slot] = r.temperature
                occupied = sum(1 for s in self.slots if s is not None)
            if not active_reqs:
                return occupied
            # temps ride along so a later fused block never samples with
            # a stale _temps_dev after this sync clears _masks_dirty
            act = self._sync_paged_device_state(active_mask, np_temps)
            logits, self.kp, self.vp, self._len_dev = self._decode_paged(
                self.params, self._last, self.kp, self.vp, self._pt_dev,
                self._len_dev, act)
            self._len_host += active_mask
        else:
            logits, self.cache = self._decode(
                self.params, self._last, self.cache, jnp.asarray(active_mask))
        temps = [0.0] * self.max_slots
        with self.lock:
            for r in self.slots:
                if r is not None:
                    temps[r.slot] = r.temperature
        toks = np.asarray(self._sample(logits, temps))
        self._last = jnp.asarray(toks[:, None].astype(np.int32))
        now = time.time()
        for r in list(active_reqs):
            if r.slot < 0:
                continue
            tok = int(toks[r.slot])
            r.generated.append(tok)
            if r.first_token_time is None:
                self._record_first_token(r, now)
            self.metrics["tokens_generated"] += 1
            self._m_tokens.inc()
            self._maybe_finish(r)
            r.progress.set()
        with self.lock:
            return sum(1 for s in self.slots if s is not None)

    def step_n(self, n: int = 8) -> int:
        """Admit, then run up to n FUSED decode steps (one host sync).
        n is clamped so no active slot can outrun its token budget or the
        cache; mid-block EOS costs a few wasted device steps (the slot's
        surplus tokens are discarded host-side), the same trade vLLM-
        style engines make for multi-step scheduling."""
        import jax
        import jax.numpy as jnp

        t_adm = time.time()
        self._admit()
        adm = time.time() - t_adm
        self.metrics["admit_s"] = self.metrics.get("admit_s", 0.0) + adm
        self._m_admit.inc(adm)
        with self.lock:
            active_reqs = [r for r in self.slots if self._decode_ready(r)]
            active_mask = np.array(
                [1 if self._decode_ready(s) else 0 for s in self.slots],
                np.int32)
            temps = np.zeros((self.max_slots,), np.float32)
            for r in active_reqs:
                temps[r.slot] = r.temperature
            occupied = sum(1 for s in self.slots if s is not None)
        if not active_reqs:
            return occupied
        n_eff = n
        for r in active_reqs:
            n_eff = min(n_eff,
                        r.max_new_tokens - len(r.generated),
                        self.max_seq - 1 - self._seq_len(r))
        # round DOWN to a power of two: every distinct n is a separate
        # XLA compilation of the n-step scan, so bound the set to
        # {1, 2, 4, ..., n} (same bucketing idea as prefill)
        b = 1
        while b * 2 <= n_eff:
            b *= 2
        n_eff = b
        if self.kv_layout == "paged" and n_eff >= 1:
            n_cap = self._ensure_paged_capacity(n_eff)
            while n_eff > max(n_cap, 1):
                n_eff //= 2
            # capacity growth may have preempted a slot — re-snapshot
            with self.lock:
                active_reqs = [r for r in self.slots
                               if self._decode_ready(r)]
                active_mask = np.array(
                    [1 if self._decode_ready(s) else 0
                     for s in self.slots], np.int32)
                temps = np.zeros((self.max_slots,), np.float32)
                for r in active_reqs:
                    temps[r.slot] = r.temperature
                occupied = sum(1 for s in self.slots if s is not None)
            if not active_reqs:
                return occupied
        if n_eff <= 1:
            return self.step()
        t_blk = time.time()
        if self.kv_layout == "paged":
            act = self._sync_paged_device_state(active_mask, temps)
            (toks, self._last, self.kp, self.vp, self._len_dev,
             self._key) = self._decode_n_paged(
                self.params, self._last, self.kp, self.vp, self._pt_dev,
                self._len_dev, act, self._temps_dev, self._key, n_eff)
            self._len_host += active_mask.astype(np.int64) * n_eff
        else:
            if self._masks_dirty:
                self._active_dev = jnp.asarray(active_mask)
                self._temps_dev = jnp.asarray(temps)
                self._masks_dirty = False
            toks, self._last, self.cache, self._key = self._decode_n(
                self.params, self._last, self.cache,
                self._active_dev, self._temps_dev, self._key, n_eff)
        toks = np.asarray(toks)  # the block's single host fetch
        now = time.time()
        # per-block wall (dispatch + device + the one fetch): attributes
        # serving throughput between engine time and transport weather
        self.metrics["decode_block_s"] = \
            self.metrics.get("decode_block_s", 0.0) + (now - t_blk)
        self.metrics["decode_blocks"] = \
            self.metrics.get("decode_blocks", 0) + 1
        self.metrics["decode_block_tokens"] = \
            self.metrics.get("decode_block_tokens", 0) + n_eff
        self._m_decode_block.observe(now - t_blk)
        for r in list(active_reqs):
            for j in range(n_eff):
                if r.slot < 0:
                    break  # finished mid-block; surplus tokens dropped
                r.generated.append(int(toks[j, r.slot]))
                if r.first_token_time is None:   # defensive: admission
                    self._record_first_token(r, now)  # normally did this
                self.metrics["tokens_generated"] += 1
                self._m_tokens.inc()
                self._maybe_finish(r)
            r.progress.set()
        with self.lock:
            return sum(1 for s in self.slots if s is not None)

    def generate(self, prompt: List[int], max_new_tokens: int = 32,
                 temperature: float = 0.0, decode_block: int = 8) -> List[int]:
        """Synchronous convenience: submit + drive until done."""
        req = self.submit(prompt, max_new_tokens, temperature)
        while not req.done_event.is_set():
            self.step_n(decode_block)
        return req.generated

    # ---- disagg KV handoff (serve/kv_transfer.py) --------------------------

    def export_kv_pages(self, pages: List[int]):
        """Host-side gather of physical KV pages for a prefill->decode
        handoff (paged layout only; call under self.lock so a reclaim
        can't recycle the pages mid-gather). Returns (k, v) numpy arrays
        shaped (n_layers, n_kv_heads, len(pages), page_size, head_dim) —
        the payload one page-group store object carries."""
        assert self.kv_layout == "paged", "export needs kv_layout='paged'"
        idx = self._jnp.asarray(pages, self._jnp.int32)
        return (np.asarray(self.kp[:, :, idx]),
                np.asarray(self.vp[:, :, idx]))

    def import_kv_pages(self, page_hashes: List[bytes], k, v) -> int:
        """Adopt externally-exported KV pages (disagg decode side):
        allocate physical pages, write the payload in one scatter per
        pool array, and register them under their chain hashes. Imported
        pages park refcount-0/evictable exactly like pages a released
        slot leaves behind, so the next submit's _try_admit_cached
        adopts them with zero prefill compute — decode never re-runs the
        prefix's prefill. Returns the number of NEW pages written
        (already-registered hashes are reused, not rewritten)."""
        assert self.kv_layout == "paged", "import needs kv_layout='paged'"
        jnp = self._jnp
        with self.lock:
            pairs = self.pool.import_pages(list(page_hashes))
            new = [(i, p) for i, (p, is_new) in enumerate(pairs) if is_new]
            if not new:
                return 0
            sel = [i for i, _ in new]
            idx = jnp.asarray([p for _, p in new], jnp.int32)
            self.kp = self.kp.at[:, :, idx].set(
                jnp.asarray(np.asarray(k)[:, :, sel], self.kp.dtype))
            self.vp = self.vp.at[:, :, idx].set(
                jnp.asarray(np.asarray(v)[:, :, sel], self.vp.dtype))
            return len(new)


class LLMServer:
    """Serve deployment hosting an engine; a background thread drives the
    decode loop so concurrent requests batch continuously."""

    def __init__(self, preset: str = "tiny", max_slots: int = 8,
                 eos_token: int = -1, params=None, cfg=None,
                 decode_block: int = 8, mode: str = "monolithic",
                 group_pages: Optional[int] = None,
                 retained_groups: Optional[int] = None,
                 use_directory: bool = True,
                 multiplexed: bool = False,
                 max_models: Optional[int] = None,
                 models: Optional[Dict[str, dict]] = None, **kw):
        if mode not in ("monolithic", "prefill", "decode"):
            raise ValueError(f"unknown LLMServer mode {mode!r}")
        if multiplexed and mode != "monolithic":
            raise ValueError("model multiplexing needs mode='monolithic'")
        if mode != "monolithic":
            # disagg handoff is expressed in physical KV pages + chain
            # hashes: contiguous caches have neither
            kw.setdefault("kv_layout", "paged")
            if kw["kv_layout"] != "paged" or not kw.get("prefix_caching",
                                                        True):
                raise ValueError("disagg modes need kv_layout='paged' "
                                 "with prefix_caching on")
        from ray_tpu.core.config import GLOBAL_CONFIG as _gc
        self.mode = mode
        self.group_pages = (group_pages if group_pages is not None
                            else _gc.serve_disagg_group_pages)
        self.retained_groups = (retained_groups if retained_groups
                                is not None
                                else _gc.serve_disagg_retained_groups)
        self.use_directory = use_directory
        self._exporter = None   # lazy: needs the in-actor runtime
        self._adopter = None
        self.engine = LLMEngine(cfg=cfg, params=params, preset=preset,
                                max_slots=max_slots, eos_token=eos_token, **kw)
        # --- model multiplexing (serve/multiplex.py) ------------------------
        # Model id "" (or absent) always means the default engine above;
        # named models resolve through a _ModelCache of per-model
        # LLMEngines bounded by serve_max_models_per_replica. The LRU's
        # unloader parks the evicted engine on `_retiring` so the decode
        # loop finishes its in-flight generations before dropping it —
        # evicting a busy model must not kill live streams.
        self.multiplexed = multiplexed
        self._engine_kwargs = dict(cfg=cfg, params=params, preset=preset,
                                   max_slots=max_slots, eos_token=eos_token,
                                   **kw)
        self._model_spec: Dict[str, dict] = dict(models or {})
        self._model_registry = None   # lazy: needs the in-actor runtime
        # `_retiring` is shared between the event-loop thread (unloader
        # appends) and the decode thread (filter-reassign): both sides
        # take this lock, or an engine appended mid-filter is lost and
        # its in-flight streams never step again
        self._retire_lock = threading.Lock()
        self._retiring: List[LLMEngine] = []
        self._unpublished: set = set()
        from ray_tpu.serve.multiplex import _ModelCache
        self._models = _ModelCache(
            type(self)._load_model,
            max_models if max_models is not None
            else _gc.serve_max_models_per_replica,
            unloader=type(self)._unload_model)
        # fused decode steps per host sync (1 = lowest latency per token,
        # higher = fewer host round-trips; new arrivals wait at most one
        # block for admission)
        self.decode_block = decode_block
        self._wake = threading.Event()
        self._stop = False
        self._draining = False
        # decode-loop progress beacon: armed while the engine has
        # admitted work, ticked per decode block — a wedged device step
        # (or a deadlocked engine lock) flags as a StallEvent instead of
        # silently freezing every in-flight stream
        from ray_tpu.observability import health as _health
        self._beacon = _health.beacon("serve:decode", deadline_s=30.0)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _engines(self) -> List["LLMEngine"]:
        """Every engine the decode loop must drive: default + resident
        multiplexed models + evicted-but-still-busy retirees. Runs on
        the decode thread while the event loop loads/evicts models, so
        it reads the cache's immutable snapshot — never the live
        OrderedDict."""
        engines = [self.engine]
        engines.extend(self._models.values_snapshot())
        with self._retire_lock:
            engines.extend(self._retiring)
        return engines

    def _loop(self):
        while not self._stop:
            worked = False
            try:
                for eng in self._engines():
                    if eng.has_work():
                        if not self._beacon.busy:
                            self._beacon.arm(queue=self.queue_len())
                        eng.step_n(self.decode_block)
                        self._beacon.tick()
                        worked = True
                if self._retiring:
                    # a retiree with no admitted work left has finished
                    # its in-flight generations; drop it (engine GC
                    # frees pages)
                    with self._retire_lock:
                        self._retiring = [e for e in self._retiring
                                          if e.has_work()]
            except Exception:
                # one engine's bad step must not kill the decode thread
                # — that would freeze every stream on the replica, not
                # just the failing one
                logger.exception("decode loop step failed; continuing")
                time.sleep(0.05)
                continue
            if not worked:
                self._beacon.disarm()
                self._wake.wait(timeout=0.01)
                self._wake.clear()
        self._beacon.disarm()

    # ---- model multiplexing ------------------------------------------------

    def _registry(self):
        if self._model_registry is None:
            from ray_tpu.serve.multiplex import ModelRegistry
            self._model_registry = ModelRegistry()
        return self._model_registry

    def _fetch_published(self, model_id: str):
        """Blocking: resolve published weights from the object store.
        Returns None ONLY when the id is genuinely unpublished (the
        engine then inits from its preset/spec). Registry or fetch
        failures propagate so the load fails loudly — a transient store
        timeout must not silently serve default weights under the
        requested model id."""
        reg = self._registry()
        if not reg.contains(model_id):
            return None
        return reg.fetch(model_id)

    async def _load_model(self, model_id: str) -> "LLMEngine":
        """_ModelCache loader: build the per-model engine. Weights come
        from the ModelRegistry when published (one pinned store copy
        shared by every replica on the node); engine construction (jit
        compiles) runs off the event loop."""
        params = await asyncio.to_thread(self._fetch_published, model_id)
        kw = dict(self._engine_kwargs)
        kw.update(self._model_spec.get(model_id, {}))
        if params is not None:
            kw["params"] = params
        return await asyncio.to_thread(LLMEngine, **kw)

    def _unload_model(self, model_id: str, engine: "LLMEngine"):
        """_ModelCache unloader: retire, don't kill — the decode loop
        keeps driving the engine until its in-flight generations finish,
        then drops the last reference (page pool + weights free)."""
        with self._retire_lock:
            self._retiring.append(engine)
        self._wake.set()

    async def _engine_for(self, model_id: str) -> "LLMEngine":
        if not model_id:
            return self.engine
        if not self.multiplexed:
            raise LLMQueueFull(
                f"replica is not multiplexed; cannot serve model "
                f"{model_id!r}")
        eng = await self._models.get(self, model_id)
        self._wake.set()
        return eng

    async def load_model(self, model_id: str) -> List[str]:
        """Controller scale-up entry: warm-load `model_id` on this
        replica and (re)publish it to the router-visible set."""
        self._unpublished.discard(model_id)
        await self._engine_for(model_id)
        return self.loaded_models()

    def unpublish_model(self, model_id: str) -> bool:
        """Controller scale-down step 1: stop advertising the model so
        routers drain away; the engine stays resident until
        unload_model()."""
        if model_id in self._models.cache:
            self._unpublished.add(model_id)
            return True
        return False

    async def unload_model(self, model_id: str) -> bool:
        """Controller scale-down step 2 (after the per-model queue
        drains): evict the engine through the retiring path."""
        self._unpublished.discard(model_id)
        return await self._models.unload(self, model_id)

    def loaded_models(self) -> List[str]:
        """Models this replica ADVERTISES (resident minus draining) —
        what rides report_load to the router/controller."""
        return [m for m in self._models.models()
                if m not in self._unpublished]

    def model_queue_len(self, model_id: str) -> int:
        """Backlog of one model's engine (0 if not resident) — the
        controller's unpublish->drain->unload poll target."""
        eng = self._models.cache.get(model_id)
        if eng is None:
            return 0
        with eng.lock:
            return (len(eng.pending)
                    + sum(1 for s in eng.slots if s is not None))

    def model_stats(self) -> Dict[str, Any]:
        """Per-model view for the controller's autoscaler tick."""
        return {
            "models": self.loaded_models(),
            "resident": self._models.models(),
            "queues": {m: self.model_queue_len(m)
                       for m in self._models.models()},
            "loads": self._models.load_count,
            "evictions": self._models.eviction_count,
            "retiring": len(self._retiring),
            "draining": self._draining,
        }

    async def __call__(self, request) -> Dict[str, Any]:
        # handle-call payloads arrive as dicts; HTTP POSTs arrive as
        # http_proxy.Request objects (same duality stream_request handles)
        if not isinstance(request, dict):
            request = request.json()
        prompt = list(request["prompt"])
        from ray_tpu.serve.multiplex import get_multiplexed_model_id
        model = str(request.get("model") or get_multiplexed_model_id() or "")
        try:
            if self._draining:
                raise LLMQueueFull("replica draining; retry elsewhere")
            if model and model in self._unpublished:
                raise LLMQueueFull(f"model {model!r} draining on this "
                                   "replica; retry elsewhere")
            eng = await self._engine_for(model)
            req = eng.submit(prompt,
                             int(request.get("max_new_tokens", 32)),
                             float(request.get("temperature", 0.0)))
        except LLMQueueFull as e:
            from ray_tpu.serve.http_proxy import Response

            return Response({"error": str(e)}, status_code=429,
                            headers={"Retry-After": "1"})
        except Exception as e:
            from ray_tpu.serve.http_proxy import Response

            return Response({"error": f"model load failed: {e}"},
                            status_code=500)
        self._wake.set()
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, req.done_event.wait)
        if req.error:
            from ray_tpu.serve.http_proxy import Response

            return Response({"error": req.error}, status_code=400)
        ttft = (req.first_token_time - req.submit_time
                if req.first_token_time else None)
        return {"tokens": req.generated, "ttft_s": ttft}

    async def stream_request(self, request) -> Any:
        """Token-streaming endpoint (the proxy's streaming contract; ref:
        serve response streaming): yields each newly generated token batch
        as soon as the decode loop lands it, finishing with a stats line.
        `request` is an http_proxy.Request (?stream=1) or a plain dict
        (handle calls)."""
        body = request if isinstance(request, dict) else request.json()
        from ray_tpu.serve.multiplex import get_multiplexed_model_id
        model = str(body.get("model") or get_multiplexed_model_id() or "")
        try:
            if self._draining:
                raise LLMQueueFull("replica draining; retry elsewhere")
            if model and model in self._unpublished:
                raise LLMQueueFull(f"model {model!r} draining on this "
                                   "replica; retry elsewhere")
            eng = await self._engine_for(model)
            req = eng.submit(list(body["prompt"]),
                             int(body.get("max_new_tokens", 32)),
                             float(body.get("temperature", 0.0)))
        except LLMQueueFull as e:
            # streaming contract has no status line mid-stream: shed as a
            # typed first frame so clients can back off like on the 429
            yield {"error": str(e), "status": 429, "done": True}
            return
        except Exception as e:
            # model load failed: typed 503 first frame — the router
            # avoids this replica and retries the stream elsewhere
            yield {"error": f"model load failed: {e}", "status": 503,
                   "done": True}
            return
        self._wake.set()
        loop = asyncio.get_running_loop()
        # stream-progress beacon (shared across this replica's streams):
        # ticked per yielded frame, armed while any stream is waiting on
        # the decode loop — no frames across the deadline = stall
        from ray_tpu.observability import health as _health
        sbeacon = _health.beacon("serve:stream", deadline_s=60.0)
        if not sbeacon.busy:
            sbeacon.arm(streaming=True)
        cursor = 0
        while True:
            new = req.generated[cursor:]
            if new:
                cursor += len(new)
                sbeacon.tick()
                yield {"tokens": new}
            elif req.done_event.is_set():
                # done was observed AFTER an empty snapshot; tokens may
                # have landed between the two — drain once more
                new = req.generated[cursor:]
                if new:
                    cursor += len(new)
                    yield {"tokens": new}
                break
            else:
                req.progress.clear()
                if len(req.generated) > cursor or req.done_event.is_set():
                    continue   # progress raced the clear
                await loop.run_in_executor(None, req.progress.wait, 1.0)
        ttft = (req.first_token_time - req.submit_time
                if req.first_token_time else None)
        sbeacon.tick()
        sbeacon.disarm()
        out = {"done": True, "n_tokens": cursor, "ttft_s": ttft}
        if req.error:
            out["error"] = req.error
        yield out

    # ---- disaggregated serving (serve/disagg.py) ---------------------------

    def _ensure_transfer(self):
        """Lazily build the kv_transfer plumbing — both ends need the
        in-actor runtime (zero-copy put/get + gcs_call)."""
        from ray_tpu.serve.kv_transfer import (HandoffAdopter,
                                               HandoffExporter,
                                               PrefixDirectory)
        if self._adopter is None:
            self._adopter = HandoffAdopter()
        if self._exporter is None and self.mode == "prefill":
            import uuid
            directory = PrefixDirectory() if self.use_directory else None
            self._exporter = HandoffExporter(
                owner=f"llm-{uuid.uuid4().hex[:12]}",
                page_tokens=self.engine.pool.page_size,
                group_pages=self.group_pages,
                retained_groups=self.retained_groups,
                directory=directory)

    async def prefill_request(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """mode="prefill": fill the prompt's KV pages (one generated
        token's worth of engine work — prefill + registration; the token
        is discarded, decode regenerates it bitwise-identically at
        temperature 0), export the leading full page GROUPS through the
        zero-copy store, and return the handoff envelope."""
        assert self.mode == "prefill", self.mode
        self._ensure_transfer()
        body = body if isinstance(body, dict) else body.json()
        prompt = list(body["prompt"])
        res = await self.__call__({"prompt": prompt, "max_new_tokens": 1,
                                   "temperature": 0.0})
        if not isinstance(res, dict):   # Response: shed or engine error
            status = getattr(res, "status_code", 500)
            return {"error": (res.body or {}).get("error", "prefill failed"),
                    "status": status}
        from ray_tpu.serve.paged_kv import page_chain_hashes
        eng = self.engine
        ps = eng.pool.page_size
        per_page = page_chain_hashes(prompt, ps)
        with eng.lock:
            cached = eng.pool.match_prefix(per_page)
        # export only groups whose every page is registered (admission
        # keeps >=1 tail token un-paged, so the final partial group
        # never exports — the decode side tail-prefills it)
        n_groups = len(cached) // self.group_pages
        export_tokens = prompt[:n_groups * self.group_pages * ps]

        def payload_for_group(s: int, e: int) -> dict:
            p0, p1 = s // ps, e // ps
            with eng.lock:
                pages = eng.pool.match_prefix(per_page[:p1])[p0:p1]
                if len(pages) != p1 - p0:
                    raise RuntimeError("page group evicted before export")
                k, v = eng.export_kv_pages(pages)
            return {"k": k, "v": v, "page_hashes": per_page[p0:p1]}

        # store puts + directory registration are blocking runtime calls
        # — banned on the event-loop thread (raylint blocking-in-async)
        envelope = await asyncio.to_thread(
            self._exporter.export,
            export_tokens, payload_for_group,
            lambda p: int(p["k"].nbytes) + int(p["v"].nbytes),
            prompt_len=len(prompt))
        return {"envelope": envelope,
                "matched_tokens": len(export_tokens)}

    def ack_handoff(self, handoff_id: str) -> bool:
        if self._exporter is None:
            return False
        return self._exporter.ack(handoff_id)

    async def adopt_decode(self, envelope: Dict[str, Any], body) -> Any:
        """mode="decode": map the envelope's page groups in from the
        store (engine.import_kv_pages — registered + evictable, no
        prefill compute), then serve the request through the normal
        streaming path: admission's _try_admit_cached adopts the
        imported pages and only the un-paged tail prefills."""
        assert self.mode == "decode", self.mode
        self._ensure_transfer()
        try:
            # blocking zero-copy gets: executor thread, not the loop
            payloads = await asyncio.to_thread(self._adopter.adopt, envelope)
            for payload in payloads:
                self.engine.import_kv_pages(payload["page_hashes"],
                                            payload["k"], payload["v"])
        except Exception:
            # exporter (or its store) died before we mapped the pages
            # in: tell the router to re-prefill on a survivor
            yield {"handoff_lost": True, "done": True}
            return
        async for frame in self.stream_request(body):
            if isinstance(frame, dict) and frame.get("done") \
                    and "handoff_id" not in frame and not frame.get("error"):
                frame = dict(frame)
                frame["handoff_id"] = envelope.get("handoff_id")
            yield frame

    def queue_len(self) -> int:
        """Engine-side backlog: requests queued for admission plus slots
        mid-generation. The serve Replica adds this to its own RPC
        in-flight count, so the controller's autoscaler and the LLM
        router's pressure score both see work the engine has ACCEPTED
        but not finished — not just the RPCs currently parked in
        stream_request. Multiplexed replicas sum across every engine
        (default + per-model + retiring)."""
        total = 0
        for eng in self._engines():
            with eng.lock:
                total += (len(eng.pending)
                          + sum(1 for s in eng.slots if s is not None))
        return total

    def drain(self) -> None:
        """Stop accepting new work; in-flight generations run to
        completion. New submissions shed with LLMQueueFull, which the
        LLM router reads as 'route elsewhere' — the scale-down protocol
        (ServeController._drain_then_kill) then polls queue_len() to 0
        before killing the actor."""
        self._draining = True
        if self._exporter is not None:
            # unpin retained + in-flight page groups and withdraw our
            # global-directory entries before the controller kills us
            self._exporter.close()

    def stats(self) -> Dict[str, Any]:
        m = dict(self.engine.metrics)
        with self.engine.lock:
            m["pending"] = len(self.engine.pending)
            m["active_slots"] = sum(
                1 for s in self.engine.slots if s is not None)
            m["max_slots"] = self.engine.max_slots
        m["draining"] = self._draining
        m["mode"] = self.mode
        if self.multiplexed:
            # advertised set + per-model backlog: the router folds these
            # into its stats map (warm-replica routing) and report_load
            # (per-model autoscaling)
            m["models"] = self.loaded_models()
            m["model_queue"] = {mm: self.model_queue_len(mm)
                                for mm in self._models.models()}
            m["model_loads"] = self._models.load_count
            m["model_evictions"] = self._models.eviction_count
        if self._exporter is not None:
            m.update({f"handoff_{k}": v
                      for k, v in self._exporter.stats().items()})
        if self._adopter is not None:
            m.update({f"adopt_{k}": v
                      for k, v in self._adopter.stats().items()})
        if m["ttft_count"]:
            m["mean_ttft_s"] = m["ttft_sum"] / m["ttft_count"]
            p50 = self.engine._m_ttft.quantile(0.5)
            if p50 is not None:
                m["ttft_p50_s"] = p50
                m["ttft_p99_s"] = self.engine._m_ttft.quantile(0.99)
        if getattr(self.engine, "pool", None) is not None:
            m["prefix_cache"] = self.engine.pool.cache_stats()
        return m
