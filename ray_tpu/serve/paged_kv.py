"""Host-side page allocator for the paged KV cache.

The device side (pools + kernel) is ops/paged_attention.py +
llama.decode_step_paged; this is the bookkeeping half: a free list of
physical pages and the per-slot page tables (ref: vLLM's BlockAllocator
/ BlockTable split, re-shaped so the device arrays stay static — the
table is a dense [slots, max_pages] int32 the engine re-uploads only
when membership changes).

Page 0 is reserved as the TRASH page: inactive slots and padding
positions write there, so the allocator never hands it out.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class PagePool:
    def __init__(self, num_pages: int, page_size: int, max_slots: int,
                 max_pages_per_slot: int):
        assert num_pages >= 2, "need at least one real page beyond trash"
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_slot = max_pages_per_slot
        # LIFO free list; page 0 reserved as trash
        self.free: List[int] = list(range(num_pages - 1, 0, -1))
        self.table = np.zeros((max_slots, max_pages_per_slot), np.int32)
        self.owned: List[List[int]] = [[] for _ in range(max_slots)]

    @property
    def free_pages(self) -> int:
        return len(self.free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self.free)

    def pages_for(self, tokens: int) -> int:
        return -(-tokens // self.page_size)

    def can_fit(self, tokens: int) -> bool:
        return self.pages_for(tokens) <= len(self.free)

    def grow(self, slot: int, total_tokens: int) -> bool:
        """Ensure `slot` owns enough pages for total_tokens. Returns
        False (allocating nothing) if the pool can't satisfy it."""
        need = self.pages_for(total_tokens)
        if need > self.max_pages_per_slot:
            return False
        extra = need - len(self.owned[slot])
        if extra <= 0:
            return True
        if extra > len(self.free):
            return False
        for _ in range(extra):
            p = self.free.pop()
            self.table[slot, len(self.owned[slot])] = p
            self.owned[slot].append(p)
        return True

    def release(self, slot: int) -> None:
        self.free.extend(reversed(self.owned[slot]))
        self.owned[slot] = []
        self.table[slot] = 0
