"""Host-side page allocator for the paged KV cache.

The device side (pools + kernel) is ops/paged_attention.py +
llama.decode_step_paged; this is the bookkeeping half: a free list of
physical pages and the per-slot page tables (ref: vLLM's BlockAllocator
/ BlockTable split, re-shaped so the device arrays stay static — the
table is a dense [slots, max_pages] int32 the engine re-uploads only
when membership changes).

Automatic prefix caching (ref: vLLM's hash-based BlockAllocatorV2):
pages are REFCOUNTED, and a full page of prompt tokens can be
registered under its chain hash (hash of the page's tokens + all
preceding pages' hash). A later prompt whose leading full pages hash
identically ADOPTS those physical pages — the prefill compute and the
page memory for the shared prefix are both skipped. Shared pages are
never written: the engine only matches FULL pages and decode always
appends past the end of the sequence. When a page's refcount drops to
zero it parks in an LRU of evictable cached pages — still matchable —
and is reclaimed to the free list only under pool pressure.

Page 0 is reserved as the TRASH page: inactive slots and padding
positions write there, so the allocator never hands it out.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

_memory_mod = None


def _memattr():
    """Lazy memory-attribution tracker (keeps this module import-light)."""
    global _memory_mod
    if _memory_mod is None:
        from ray_tpu.observability import memory
        _memory_mod = memory.tracker()
    return _memory_mod


def page_chain_hashes(tokens, page_size: int) -> List[bytes]:
    """Chain hash per FULL page of `tokens`: h_i = H(h_{i-1} || page_i).
    Position-dependent by construction, so page content alone never
    collides across different prefixes."""
    n_full = len(tokens) // page_size
    out, chain = [], b""
    for i in range(n_full):
        page = np.asarray(tokens[i * page_size:(i + 1) * page_size],
                          np.int32).tobytes()
        chain = hashlib.blake2b(chain + page, digest_size=16).digest()
        out.append(chain)
    return out


class PagePool:
    def __init__(self, num_pages: int, page_size: int, max_slots: int,
                 max_pages_per_slot: int, page_nbytes: int = 0):
        assert num_pages >= 2, "need at least one real page beyond trash"
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_slot = max_pages_per_slot
        # device bytes per physical page (K+V across layers); when the
        # engine provides it, occupied pages register with the memory
        # plane as a synthetic "kv" record (see _track_mem)
        self.page_nbytes = int(page_nbytes)
        self._mem_key = f"kvpool:{id(self):x}"
        self._mem_tracked = False
        # LIFO free list; page 0 reserved as trash
        self.free: List[int] = list(range(num_pages - 1, 0, -1))
        self.table = np.zeros((max_slots, max_pages_per_slot), np.int32)
        self.owned: List[List[int]] = [[] for _ in range(max_slots)]
        # prefix cache state
        self.ref = np.zeros((num_pages,), np.int32)
        self.hash_to_page: Dict[bytes, int] = {}
        self.page_to_hash: Dict[int, bytes] = {}
        # refcount-0 registered pages, oldest first (reclaim order)
        self.evictable: "OrderedDict[int, None]" = OrderedDict()
        # bumped on every table write (grow/adopt/release): the engine
        # re-uploads the device table when this moves — inferring it
        # from used_pages misses cache-reclaim-served growth (net 0)
        self.table_version = 0

    @property
    def free_pages(self) -> int:
        return len(self.free)

    @property
    def available_pages(self) -> int:
        """Free now plus reclaimable-from-cache (grow() reclaims on
        demand) — capacity prechecks must use THIS, not free_pages, or
        a warm cache would make the pool look artificially full."""
        return len(self.free) + len(self.evictable)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self.free)

    def pages_for(self, tokens: int) -> int:
        return -(-tokens // self.page_size)

    def can_fit(self, tokens: int) -> bool:
        return self.pages_for(tokens) <= self.available_pages

    def _track_mem(self) -> None:
        """Mirror occupied-page bytes (incl. evictable cached pages —
        they still hold device memory) into the memory plane."""
        if not self.page_nbytes:
            return
        held = self.used_pages   # includes parked evictable pages
        mem = _memattr()
        if held > 0:
            mem.attribute(self._mem_key, "kv", held * self.page_nbytes,
                          store=False, pages=held,
                          evictable=len(self.evictable))
            self._mem_tracked = True
        elif self._mem_tracked:
            mem.release(self._mem_key)
            self._mem_tracked = False

    def _unregister(self, page: int) -> None:
        h = self.page_to_hash.pop(page, None)
        if h is not None and self.hash_to_page.get(h) == page:
            del self.hash_to_page[h]

    def _reclaim(self, n: int) -> int:
        """Evict up to n refcount-0 cached pages (LRU) to the free list."""
        got = 0
        while got < n and self.evictable:
            page, _ = self.evictable.popitem(last=False)
            self._unregister(page)
            self.free.append(page)
            got += 1
        return got

    def grow(self, slot: int, total_tokens: int) -> bool:
        """Ensure `slot` owns enough pages for total_tokens. Returns
        False (allocating nothing) if the pool can't satisfy it."""
        need = self.pages_for(total_tokens)
        if need > self.max_pages_per_slot:
            return False
        extra = need - len(self.owned[slot])
        if extra <= 0:
            return True
        if extra > len(self.free):
            self._reclaim(extra - len(self.free))
        if extra > len(self.free):
            return False
        for _ in range(extra):
            p = self.free.pop()
            self.table[slot, len(self.owned[slot])] = p
            self.owned[slot].append(p)
            self.ref[p] = 1
        self.table_version += 1
        self._track_mem()
        return True

    def release(self, slot: int) -> None:
        for p in reversed(self.owned[slot]):
            self.ref[p] -= 1
            if self.ref[p] <= 0:
                self.ref[p] = 0
                if p in self.page_to_hash:
                    # cached: park, still matchable until reclaimed
                    self.evictable[p] = None
                else:
                    self.free.append(p)
        self.owned[slot] = []
        self.table[slot] = 0
        self.table_version += 1
        self._track_mem()

    # ---- prefix cache ------------------------------------------------------

    def match_prefix(self, hashes: List[bytes]) -> List[int]:
        """Longest run of leading hashes present in the cache; returns
        their physical pages (does NOT take references — adopt() does)."""
        pages = []
        for h in hashes:
            p = self.hash_to_page.get(h)
            if p is None:
                break
            pages.append(p)
        return pages

    def adopt(self, slot: int, pages: List[int]) -> None:
        """Append shared pages to a slot's table, taking a reference on
        each. Caller guarantees the slot's table is empty (fresh admit)."""
        for p in pages:
            self.table[slot, len(self.owned[slot])] = p
            self.owned[slot].append(p)
            self.ref[p] += 1
            self.evictable.pop(p, None)     # in use again
        self.table_version += 1
        self._track_mem()
        if len(self.owned[slot]) > self.max_pages_per_slot:
            raise ValueError("adopted prefix exceeds max_pages_per_slot")

    def register(self, slot: int, hashes: List[bytes]) -> None:
        """Register the slot's first len(hashes) pages under their chain
        hashes (post-prefill). First writer wins: an existing mapping for
        a hash is kept — duplicates converge on the earlier page as later
        prompts adopt it."""
        for i, h in enumerate(hashes):
            if i >= len(self.owned[slot]):
                break
            p = self.owned[slot][i]
            if h in self.hash_to_page or p in self.page_to_hash:
                continue
            self.hash_to_page[h] = p
            self.page_to_hash[p] = h

    def import_pages(self, hashes: List[bytes]) -> List[tuple]:
        """Allocate + register physical pages for externally-imported KV
        (disagg adopt, serve/kv_transfer.py): each new page parks
        refcount-0 in the evictable LRU — matchable by the next admit's
        _try_admit_cached, reclaimable under pool pressure, exactly like
        pages a released slot leaves behind. Returns (page, is_new)
        pairs in hash order (existing registrations are reused with
        is_new=False; the caller only writes KV into new pages). Stops
        early if the pool is exhausted."""
        out = []
        for h in hashes:
            p = self.hash_to_page.get(h)
            if p is not None:
                out.append((p, False))
                continue
            if not self.free:
                self._reclaim(1)
            if not self.free:
                break
            p = self.free.pop()
            self.hash_to_page[h] = p
            self.page_to_hash[p] = h
            self.ref[p] = 0
            self.evictable[p] = None
            out.append((p, True))
        self._track_mem()
        return out

    def cache_stats(self) -> dict:
        return {"registered": len(self.hash_to_page),
                "evictable": len(self.evictable),
                "free": len(self.free)}
