"""KV-page handoff primitives for disaggregated prefill/decode serving.

The prefill->decode handoff (serve/disagg.py) never serializes KV pages
into an RPC: the prefill replica exports each page GROUP (a fixed run of
full pages) as a first-class object-store object — one zero-copy
``ray_tpu.put`` per group, primary pinned on the prefill node — and
mails only a small ENVELOPE of ``{hash, ref, nbytes}`` records over the
router's compiled standing channel. The decode replica resolves each ref
straight out of the store (``PagePool.adopt`` semantics: map, don't
copy) and acks; the exporter holds the per-handoff refs until that ack,
so the primaries stay pinned exactly as long as an un-adopted handoff
is in flight.

Exactly-once byte movement: groups are deduplicated by their
group-boundary chain hash against the exporter's retained LRU — a
shared prefix crosses the store ONCE no matter how many requests (or
replicas, via the GCS global prefix directory) later adopt it. The
``puts`` / ``reused_groups`` counters are the transfer-accounting
evidence the bench asserts on.

Lifecycle rules (mirrored by raylint's channel-protocol rule for the
handoff hop): export -> register -> [adopt]* -> ack; an envelope must
never be enqueued after the exporter closed, and adopt-after-teardown
of the standing channel is a protocol error.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.serve.paged_kv import page_chain_hashes


def group_boundary_hashes(tokens, page_tokens: int,
                          group_pages: int) -> List[bytes]:
    """Chain hash at each full page-GROUP boundary. The chain hash of a
    group's last page commits to every token before it, so one hash per
    group identifies the whole prefix up to that boundary — directory
    lookups walk these instead of every page hash."""
    per_page = page_chain_hashes(tokens, page_tokens)
    return [per_page[i * group_pages + group_pages - 1]
            for i in range(len(per_page) // group_pages)]


class PrefixDirectory:
    """Client for the GCS-side global prefix directory (gcs.py
    rpc_prefix_*): hash -> {ref, owner, owner_node, nbytes, last_touch}.
    Thin — every method is one gcs_call — so replicas and the router
    share one code path and the sim tests can use it directly."""

    def __init__(self):
        from ray_tpu.core import runtime as rt
        self._rt = rt.get_runtime()

    def register(self, entries: List[Dict[str, Any]]) -> dict:
        return self._rt.gcs_call("prefix_register", entries=entries)

    def lookup(self, hashes: List[bytes]) -> List[Optional[dict]]:
        if not hashes:
            return []
        return self._rt.gcs_call("prefix_lookup", hashes=hashes)

    def drop(self, hashes: List[bytes], owner: str = "") -> int:
        if not hashes:
            return 0
        return self._rt.gcs_call("prefix_drop", hashes=hashes, owner=owner)

    def stats(self) -> dict:
        return self._rt.gcs_call("prefix_stats")


class HandoffExporter:
    """Prefill-side export + pin/ack bookkeeping.

    One instance per prefill replica. ``export()`` puts each NEW page
    group into the zero-copy store (dedup by group hash against the
    retained LRU), registers new groups in the global directory, and
    returns the envelope. The per-handoff ref list keeps every group's
    primary pinned until ``ack(handoff_id)`` — including groups that
    have since been evicted from the retained LRU, so an in-flight
    decode can always resolve its envelope. Retained-LRU eviction drops
    the matching directory entries (owner-scoped) before the ref dies.
    """

    def __init__(self, *, owner: str, page_tokens: int, group_pages: int,
                 retained_groups: int, directory: Optional[PrefixDirectory],
                 put: Optional[Callable[[Any], Any]] = None):
        import ray_tpu
        from ray_tpu.core import runtime as rt
        self.owner = owner
        self.page_tokens = int(page_tokens)
        self.group_pages = int(group_pages)
        self.group_tokens = self.page_tokens * self.group_pages
        self.retained_groups = int(retained_groups)
        self.directory = directory
        self._put = put or ray_tpu.put
        self._owner_node = getattr(rt.get_runtime(), "node_id", None) or ""
        # hash -> {"ref", "nbytes"}: groups whose primaries this replica
        # keeps pinned for future reuse (spill tier absorbs overflow)
        self._groups: "OrderedDict[bytes, dict]" = OrderedDict()
        self._handoffs: Dict[str, List[Any]] = {}
        self._closed = False
        self._seq = 0
        self._lock = threading.Lock()
        self.metrics: Dict[str, Any] = {
            "handoffs": 0, "puts": 0, "reused_groups": 0,
            "put_bytes": 0, "acked": 0, "unacked_expired": 0,
            "retained_evicted": 0, "export_s": 0.0}

    def export(self, tokens: List[int],
               payload_for_group: Callable[[int, int], Any],
               nbytes_of: Callable[[Any], int],
               prompt_len: Optional[int] = None) -> Dict[str, Any]:
        """Export every full page group of `tokens`. payload_for_group
        (start_token, end_token) -> object to put (device view, numpy
        pages, ...); only called for groups not already retained.
        prompt_len overrides the envelope's recorded prompt length when
        `tokens` is a truncated exportable prefix of the real prompt."""
        if self._closed:
            raise RuntimeError("HandoffExporter is closed")
        t0 = time.time()
        per_page = page_chain_hashes(tokens, self.page_tokens)
        hashes = [per_page[i * self.group_pages + self.group_pages - 1]
                  for i in range(len(per_page) // self.group_pages)]
        groups, refs, new_entries = [], [], []
        with self._lock:
            self._seq += 1
            handoff_id = f"{self.owner}:{self._seq}"
            for i, h in enumerate(hashes):
                got = self._groups.get(h)
                if got is not None:
                    self._groups.move_to_end(h)
                    self.metrics["reused_groups"] += 1
                else:
                    payload = payload_for_group(i * self.group_tokens,
                                                (i + 1) * self.group_tokens)
                    nbytes = int(nbytes_of(payload))
                    got = {"ref": self._put(payload), "nbytes": nbytes}
                    self._groups[h] = got
                    self.metrics["puts"] += 1
                    self.metrics["put_bytes"] += nbytes
                    new_entries.append({
                        "hash": h, "ref": got["ref"], "owner": self.owner,
                        "owner_node": self._owner_node, "nbytes": nbytes,
                        "group_tokens": self.group_tokens})
                groups.append({
                    "hash": h, "ref": got["ref"],
                    "nbytes": got["nbytes"],
                    "page_hashes": per_page[i * self.group_pages:
                                            (i + 1) * self.group_pages]})
                refs.append(got["ref"])
            self._handoffs[handoff_id] = refs
            self.metrics["handoffs"] += 1
            evict_hashes = []
            while len(self._groups) > self.retained_groups:
                eh, _ = self._groups.popitem(last=False)
                evict_hashes.append(eh)
                self.metrics["retained_evicted"] += 1
        if self.directory is not None:
            if new_entries:
                self.directory.register(new_entries)
            if evict_hashes:
                self.directory.drop(evict_hashes, owner=self.owner)
        self.metrics["export_s"] += time.time() - t0
        return {"handoff_id": handoff_id, "owner": self.owner,
                "page_tokens": self.page_tokens,
                "group_tokens": self.group_tokens,
                "prompt_len": (prompt_len if prompt_len is not None
                               else len(tokens)),
                "groups": groups,
                "nbytes": sum(g["nbytes"] for g in groups)}

    def has(self, h: bytes) -> bool:
        with self._lock:
            return h in self._groups

    def seed(self, entries: List[tuple]) -> None:
        """Adopt FOREIGN groups (another replica's exports, resolved via
        the global directory) into the retained map: our future
        envelopes reference the original store objects — the bytes never
        cross the store a second time. (hash, ref, nbytes) triples; the
        held ref is a borrow, so the object outlives the owner's
        eviction while we retain it. Never re-registered: the directory
        already points at the incumbent owner's entry."""
        with self._lock:
            for h, ref, nbytes in entries:
                if h not in self._groups:
                    self._groups[h] = {"ref": ref, "nbytes": int(nbytes),
                                       "foreign": True}
                self._groups.move_to_end(h)

    def ack(self, handoff_id: str) -> bool:
        """Decode adopted (or the router abandoned) this handoff: drop
        its pin-holding refs. Retained groups stay pinned via the LRU."""
        with self._lock:
            found = self._handoffs.pop(handoff_id, None) is not None
            if found:
                self.metrics["acked"] += 1
        return found

    def lookup_warm(self, tokens: List[int]) -> int:
        """Longest leading run of tokens resolvable from the GLOBAL
        directory (any owner), in tokens. 0 when no directory."""
        if self.directory is None:
            return 0
        hashes = group_boundary_hashes(tokens, self.page_tokens,
                                       self.group_pages)
        hits = self.directory.lookup(hashes)
        n = 0
        for e in hits:
            if e is None:
                break
            n += 1
        return n * self.group_tokens

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            m = dict(self.metrics)
            m["retained_groups"] = len(self._groups)
            m["inflight_handoffs"] = len(self._handoffs)
        return m

    def close(self) -> None:
        """Drain-time teardown: unpin everything — in-flight handoffs
        included (the router re-prefills on a survivor) — and withdraw
        this owner's directory entries."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self.metrics["unacked_expired"] += len(self._handoffs)
            self._handoffs.clear()
            hashes = list(self._groups)
            self._groups.clear()
        if self.directory is not None and hashes:
            try:
                self.directory.drop(hashes, owner=self.owner)
            except Exception:
                pass   # GCS may already be gone at shutdown


class HandoffAdopter:
    """Decode-side resolve: one ``ray_tpu.get`` per envelope group,
    straight out of the zero-copy tier (borrowed view — no copy for
    store-local primaries). Returns payloads in prefix order."""

    def __init__(self, *, get: Optional[Callable[[Any], Any]] = None):
        import ray_tpu
        self._get = get or ray_tpu.get
        self._lock = threading.Lock()
        self.metrics: Dict[str, Any] = {
            "adopted_groups": 0, "adopted_bytes": 0, "adopts": 0,
            "adopt_s": 0.0, "adopt_failures": 0}

    def adopt(self, envelope: Dict[str, Any]) -> List[Any]:
        t0 = time.time()
        out = []
        try:
            for g in envelope["groups"]:
                out.append(self._get(g["ref"]))
        except Exception:
            with self._lock:
                self.metrics["adopt_failures"] += 1
            raise
        with self._lock:
            self.metrics["adopts"] += 1
            self.metrics["adopted_groups"] += len(out)
            self.metrics["adopted_bytes"] += sum(
                int(g["nbytes"]) for g in envelope["groups"])
            self.metrics["adopt_s"] += time.time() - t0
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self.metrics)
