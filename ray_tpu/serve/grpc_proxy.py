"""gRPC ingress proxy for serve.

Reference: the serve gRPC driver path (serve/drivers.py gRPCIngress +
src/ray/protobuf/serve.proto) — an alternative ingress speaking gRPC
instead of HTTP. Wire contract (generic, no codegen needed on either
side): service /ray_tpu.serve.ServeAPI/Predict, request and response are
pickled python payloads:

    request  = pickle({"deployment": str, "method": str (default
                        __call__), "args": tuple, "kwargs": dict})
    response = pickle({"ok": True, "result": ...} |
                      {"ok": False, "error": str})

A typed .proto front-end can be layered on by any client; the generic
bytes contract keeps parity with the pickle-frame control plane
(ray_tpu/protobuf/services.proto documents the same envelope decision).
"""

from __future__ import annotations

import pickle
from typing import Optional

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle

GRPC_PROXY_NAME = "_serve_grpc_proxy"
METHOD_PATH = "/ray_tpu.serve.ServeAPI/Predict"


@ray_tpu.remote
class GrpcProxy:
    """One gRPC server actor fronting all deployments (ref: per-node HTTP
    proxies in http_state.py; gRPC gets one until profiling says more)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from concurrent.futures import ThreadPoolExecutor

        import grpc

        self._handles = {}

        proxy = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != METHOD_PATH:
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    proxy._predict,
                    request_deserializer=None,   # raw bytes through
                    response_serializer=None)

        self.server = grpc.server(ThreadPoolExecutor(max_workers=16))
        self.server.add_generic_rpc_handlers((_Handler(),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        self.server.start()

    def _handle(self, name: str) -> DeploymentHandle:
        if name not in self._handles:
            self._handles[name] = DeploymentHandle(name)
        return self._handles[name]

    def _predict(self, request: bytes, context) -> bytes:
        try:
            req = pickle.loads(request)
            h = self._handle(req["deployment"])
            method = req.get("method", "__call__")
            args = req.get("args", ())
            kwargs = req.get("kwargs", {})
            ref = h.remote(*args, **kwargs) if method == "__call__" \
                else h.method(method).remote(*args, **kwargs)
            result = ray_tpu.get(ref, timeout=req.get("timeout", 60.0))
            return pickle.dumps({"ok": True, "result": result})
        except Exception as e:  # surfaced to the client, proxy stays up
            return pickle.dumps({"ok": False,
                                 "error": f"{type(e).__name__}: {e}"})

    def ready(self) -> int:
        return self.port

    def shutdown(self):
        self.server.stop(grace=0.5)
        return True


def start_grpc(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start (or find) the gRPC ingress; returns the bound port
    (ref: serve.start(grpc_options=...))."""
    try:
        proxy = ray_tpu.get_actor(GRPC_PROXY_NAME, namespace="serve")
    except ValueError:
        try:
            proxy = GrpcProxy.options(
                name=GRPC_PROXY_NAME, namespace="serve",
                max_concurrency=64).remote(host, port)
        except ValueError:
            proxy = ray_tpu.get_actor(GRPC_PROXY_NAME, namespace="serve")
    return ray_tpu.get(proxy.ready.remote())


def shutdown_grpc():
    try:
        proxy = ray_tpu.get_actor(GRPC_PROXY_NAME, namespace="serve")
    except ValueError:
        return
    try:
        ray_tpu.get(proxy.shutdown.remote())
    finally:
        ray_tpu.kill(proxy)


class GrpcServeClient:
    """Minimal typed client for the generic contract (what a
    cross-language client implements against METHOD_PATH)."""

    def __init__(self, address: str):
        import grpc

        self.channel = grpc.insecure_channel(address)
        self._call = self.channel.unary_unary(METHOD_PATH)

    def predict(self, deployment: str, *args, method: str = "__call__",
                timeout: Optional[float] = None, **kwargs):
        payload = pickle.dumps({"deployment": deployment, "method": method,
                                "args": args, "kwargs": kwargs,
                                **({"timeout": timeout}
                                   if timeout is not None else {})})
        out = pickle.loads(self._call(payload, timeout=timeout))
        if not out["ok"]:
            raise RuntimeError(out["error"])
        return out["result"]

    def close(self):
        self.channel.close()
