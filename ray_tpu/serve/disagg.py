"""Disaggregated prefill/decode serving (ref: DistServe, Mooncake).

Prefill is batch-friendly and compute-bound; decode is latency-sensitive
and memory-bound — co-locating them makes each worse (a long prompt's
prefill stalls every decode stream sharing the engine). ``build_llm_app
(disaggregated=True)`` (llm_deployment.py) deploys two pools instead:

    DisaggRouter (ingress) -> {name}_prefill x N  +  {name}_decode x M

and this router runs the two-stage flow per request:

1. GLOBAL PREFIX LOOKUP — the prompt's page-GROUP chain hashes are
   resolved against the GCS global prefix directory (gcs.py
   rpc_prefix_*). A warm prefix is adoptable by ANY prefill replica, so
   the rendezvous ranking the monolithic router uses for replica-LOCAL
   cache affinity extends cluster-global: directory hits route by load,
   cold prefixes still route by rendezvous so locality builds.
2. PREFILL — ``prefill_request`` on the chosen prefill replica fills the
   paged-KV pages (skipping locally-cached AND directory-warm groups),
   exports each new page group ONCE through the zero-copy store
   (kv_transfer.HandoffExporter), and returns the handoff envelope:
   ``{handoff_id, groups: [{hash, ref, nbytes}], ...}`` — refs, never
   page bytes.
3. DECODE — the envelope rides the decode replica's compiled standing
   channel (the same per-replica graph the monolithic router uses; the
   method is an execute-time input) as ``adopt_decode(envelope, body)``;
   the decode replica maps the groups in from the store and streams
   token frames back over the channel.
4. ACK — whatever the attempt's outcome, the router acks the handoff to
   the prefill replica so the per-handoff pins release; retained groups
   stay pinned via the exporter's LRU for future reuse.

Failover keeps PR 10's token-continuity contract: a dead prefill
replica re-routes the prefill to a survivor; a decode-side death or a
``handoff_lost`` frame (exporter died before adoption) re-prefills
prompt + emitted-so-far, force-dropping the envelope's now-dangling
directory entries first. The client stream never duplicates or drops a
token.
"""

from __future__ import annotations

import asyncio
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.llm_router import LLMRouter, _next_item, prefix_hash
from ray_tpu.util import metrics as _um
from ray_tpu.util.tracing import span

_END = object()


class DisaggRouter(LLMRouter):
    """Ingress for the two-pool topology. The base class manages the
    DECODE pool end to end (stats poll, pressure, compiled standing
    channels, per-pool load report); this subclass adds the prefill pool
    view, the global-directory-aware prefill pick, and the two-stage
    request path."""

    def __init__(self, decode_handle: DeploymentHandle, *,
                 prefill_app: Optional[DeploymentHandle] = None,
                 page_tokens: Optional[int] = None,
                 group_pages: Optional[int] = None,
                 **kwargs):
        if prefill_app is None:
            raise ValueError("DisaggRouter needs prefill_app= (the bound "
                             "prefill deployment)")
        cfg = GLOBAL_CONFIG
        # set before super().__init__: the stats thread it starts runs
        # our _stats_tick, which reads these
        self._pf_handle = prefill_app
        self._pf_stats: Dict[str, Dict[str, Any]] = {}
        self._pf_inflight: Dict[str, int] = {}
        self._directory = None   # lazy: needs the in-actor runtime
        self.page_tokens = (page_tokens if page_tokens is not None
                            else cfg.serve_disagg_page_tokens)
        self.group_pages = (group_pages if group_pages is not None
                            else cfg.serve_disagg_group_pages)
        super().__init__(decode_handle, **kwargs)
        self.counters.update({
            "handoffs": 0, "handoffs_lost": 0, "prefill_reroutes": 0,
            "prefill_shed": 0, "global_lookups": 0, "global_hits": 0})
        tag = {"router": self._reporter[-12:]}
        self._m_handoff_bytes = _um.Counter(
            "ray_tpu_llm_router_handoff_bytes",
            "KV page bytes referenced by prefill->decode envelopes",
            tag_keys=("router",)).set_default_tags(tag)
        self._m_handoff_s = _um.Histogram(
            "ray_tpu_llm_router_handoff_s",
            "envelope-to-first-decode-frame latency",
            boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5],
            tag_keys=("router",)).set_default_tags(tag)
        self._m_pool_inflight = _um.Gauge(
            "ray_tpu_llm_router_pool_inflight",
            "streams in flight per pool",
            tag_keys=("router", "pool")).set_default_tags(tag)

    # ---- prefill pool view -------------------------------------------------

    def _stats_tick(self):
        super()._stats_tick()   # decode pool + its load report
        self._poll_pool(self._pf_handle, self._pf_stats)
        with self._lock:
            pf_depth = sum(self._pf_inflight.values())
            dec_depth = sum(self._inflight.values())
        self._m_pool_inflight.set(pf_depth, tags={"pool": "prefill"})
        self._m_pool_inflight.set(dec_depth, tags={"pool": "decode"})
        # prefill demand reported under the prefill deployment's name:
        # the controller's per-deployment fold autoscales each pool on
        # its OWN queue, the point of disaggregating
        self._report(self._pf_handle.deployment_name, pf_depth)

    def _pf_pressure(self, key: str) -> float:
        st = self._pf_stats.get(key, {})
        load = self._pf_inflight.get(key, 0) + st.get("pending", 0)
        return load * (1.0 + st.get("busy", 0.0))

    # ---- global prefix directory -------------------------------------------

    def _dir(self):
        if self._directory is None:
            from ray_tpu.serve.kv_transfer import PrefixDirectory
            self._directory = PrefixDirectory()
        return self._directory

    def _lookup_warm(self, tokens: List[int]) -> int:
        """Leading tokens resolvable from the global directory, any
        owner (blocking; executor thread). 0 on any directory error —
        a cold route is always correct, just slower."""
        from ray_tpu.serve.kv_transfer import group_boundary_hashes
        try:
            hashes = group_boundary_hashes(tokens, self.page_tokens,
                                           self.group_pages)
            if not hashes:
                return 0
            with self._lock:
                self.counters["global_lookups"] += 1
            hits = self._dir().lookup(hashes)
        except Exception:
            return 0
        n = 0
        for e in hits:
            if e is None:
                break
            n += 1
        return n * self.page_tokens * self.group_pages

    def _drop_dangling(self, envelope: Dict[str, Any]) -> None:
        """A handoff was lost: the envelope's refs dangle (the exporter
        or its node died), so force-drop their directory entries — the
        next prefill re-exports and re-registers fresh ones. Without
        this, first-writer-wins would pin the directory to a dead
        owner's refs forever."""
        try:
            self._dir().drop([g["hash"] for g in envelope["groups"]])
        except Exception:
            pass

    # ---- placement ---------------------------------------------------------

    def _pick_prefill(self, prompt: List[int], avoid: set,
                      warm_tokens: int) -> Tuple[str, Any]:
        """Choose a prefill replica (blocking; executor thread). Cold
        prefixes rank by rendezvous so locality builds, exactly like the
        monolithic router; a prefix warm in the GLOBAL directory is
        adoptable anywhere, so those route purely by load — the
        cluster-global extension of the local-affinity pick."""
        import random

        reps = self._snapshot_of(self._pf_handle)
        if not reps:
            reps = self._snapshot_of(self._pf_handle, force=True)
        with self._lock:
            stats = dict(self._pf_stats)
        usable = [(k, r) for k, r in reps
                  if k not in avoid
                  and not stats.get(k, {}).get("draining", False)]
        if not usable:
            usable = [(k, r) for k, r in reps if k not in avoid]
        if not usable:
            raise RuntimeError(
                f"no usable replicas for "
                f"{self._pf_handle.deployment_name!r}")
        span_attrs = {"n_replicas": len(usable),
                      "warm_tokens": warm_tokens}
        with span("llm_router.route_prefill", span_attrs):
            if len(usable) == 1:
                return usable[0]
            affinity_span = min(len(prompt), self.prefix_tokens)
            if warm_tokens >= affinity_span > 0:
                # globally warm: any replica adopts the prefix from the
                # store; load wins
                with self._lock:
                    self.counters["global_hits"] += 1
                return min(usable, key=lambda kr: self._pf_pressure(kr[0]))
            if self.policy == "random":
                return usable[random.randrange(len(usable))]
            ph = prefix_hash(prompt, self.prefix_tokens)
            ranked = sorted(
                usable, key=lambda kr: hashlib.sha1(
                    f"{ph}:{kr[0]}".encode()).digest(), reverse=True)
            mean = sum(self._pf_pressure(k) for k, _ in usable) \
                / len(usable)
            limit = self.overload_factor * max(mean, 1.0)
            for rank, (k, r) in enumerate(ranked):
                if self._pf_pressure(k) <= limit:
                    with self._lock:
                        if rank == 0:
                            self.counters["affinity_picks"] += 1
                        else:
                            self.counters["fallback_picks"] += 1
                    return k, r
            with self._lock:
                self.counters["fallback_picks"] += 1
            return min(ranked, key=lambda kr: self._pf_pressure(kr[0]))

    def _pick_decode(self, avoid: set) -> Tuple[str, Any]:
        """Decode replicas hold no prefix state — the envelope makes any
        of them equivalent — so decode placement is pure load."""
        reps = self._snapshot()
        if not reps:
            reps = self._snapshot(force=True)
        with self._lock:
            stats = dict(self._replica_stats)
        usable = [(k, r) for k, r in reps
                  if k not in avoid
                  and not stats.get(k, {}).get("draining", False)]
        if not usable:
            usable = [(k, r) for k, r in reps if k not in avoid]
        if not usable:
            raise RuntimeError(
                f"no usable replicas for {self._handle.deployment_name!r}")
        return min(usable, key=lambda kr: self._pressure(kr[0]))

    # ---- prefill + ack transport -------------------------------------------

    def _prefill_call(self, key: str, replica, sub: dict) -> dict:
        """One prefill RPC (blocking; executor thread). Request/response
        — not a stream — so it rides the plain dispatch path, not the
        standing channel."""
        ref = replica.handle_request.remote(
            "prefill_request", (sub,), {}, None)
        return ray_tpu.get(ref, timeout=60)

    def _ack(self, replica, handoff_id: str) -> None:
        """Release the handoff's pins on the prefill side. Fire-and-
        forget: a dead exporter has nothing left to unpin."""
        try:
            # raylint: disable=leaked-object-ref -- fire-and-forget ack
            replica.handle_request.remote("ack_handoff",
                                          (handoff_id,), {}, None)
        except Exception:
            pass

    # ---- request path ------------------------------------------------------

    async def stream_request(self, request) -> Any:
        """Two-stage streaming entry: admission -> global lookup ->
        prefill (envelope) -> decode stream, with failover at each
        stage. Same admission bound and client-visible frame contract as
        the monolithic router."""
        body = request if isinstance(request, dict) else request.json()
        prompt = list(body["prompt"])
        max_new = int(body.get("max_new_tokens", 32))
        temperature = float(body.get("temperature", 0.0))
        with self._lock:
            if self._total_inflight >= self.max_inflight:
                self.counters["shed"] += 1
                shed = True
            else:
                self._total_inflight += 1
                self.counters["requests"] += 1
                shed = False
            self._m_inflight.set(self._total_inflight)
        if shed:
            self._m_sheds.inc()
            yield {"error": f"router at max_inflight={self.max_inflight}; "
                            "retry later",
                   "status": 429, "retry_after_s": 1.0, "done": True}
            return
        self._m_requests.inc()
        loop = asyncio.get_running_loop()
        t0 = time.time()
        first_t: Optional[float] = None
        emitted: List[int] = []
        avoid_pf: set = set()
        avoid_dec: set = set()
        attempts = 0
        try:
            while True:
                attempts += 1
                if attempts > self.max_attempts:
                    yield {"error": "no replica could finish the stream",
                           "status": 503, "done": True,
                           "n_tokens": len(emitted)}
                    return
                sub = {"prompt": prompt + emitted,
                       "max_new_tokens": max_new - len(emitted),
                       "temperature": temperature}
                # -- stage 1: prefill ------------------------------------
                warm = await loop.run_in_executor(
                    self._executor, self._lookup_warm, sub["prompt"])
                try:
                    pf_key, pf_replica = await loop.run_in_executor(
                        self._executor, self._pick_prefill, sub["prompt"],
                        avoid_pf, warm)
                except RuntimeError as e:
                    yield {"error": str(e), "status": 503, "done": True,
                           "n_tokens": len(emitted)}
                    return
                with self._lock:
                    self._pf_inflight[pf_key] = \
                        self._pf_inflight.get(pf_key, 0) + 1
                try:
                    res = await loop.run_in_executor(
                        self._executor, self._prefill_call, pf_key,
                        pf_replica, sub)
                except (ray_tpu.exceptions.ActorDiedError,
                        ray_tpu.exceptions.ActorUnavailableError) as e:
                    self._on_prefill_death(pf_key, e)
                    avoid_pf.add(pf_key)
                    continue
                except Exception as e:
                    # prefill RPC failed some other way: avoid + retry
                    with self._lock:
                        self.counters["prefill_reroutes"] += 1
                    avoid_pf.add(pf_key)
                    if attempts >= self.max_attempts:
                        yield {"error": f"prefill failed: {e}",
                               "status": 503, "done": True,
                               "n_tokens": len(emitted)}
                        return
                    continue
                finally:
                    with self._lock:
                        if self._pf_inflight.get(pf_key, 0) > 0:
                            self._pf_inflight[pf_key] -= 1
                if res.get("status") == 429:
                    with self._lock:
                        self.counters["prefill_shed"] += 1
                    avoid_pf.add(pf_key)
                    continue
                envelope = res["envelope"]
                t_env = time.time()
                with self._lock:
                    self.counters["handoffs"] += 1
                self._m_handoff_bytes.inc(int(envelope.get("nbytes", 0)))
                # -- stage 2: decode -------------------------------------
                try:
                    dec_key, dec_replica = await loop.run_in_executor(
                        self._executor, self._pick_decode, avoid_dec)
                except RuntimeError as e:
                    self._ack(pf_replica, envelope["handoff_id"])
                    yield {"error": str(e), "status": 503, "done": True,
                           "n_tokens": len(emitted)}
                    return
                with self._lock:
                    self._inflight[dec_key] = \
                        self._inflight.get(dec_key, 0) + 1
                rerouted = False
                handoff_seen = False
                try:
                    frames = await loop.run_in_executor(
                        self._executor, self._open_stream, dec_key,
                        dec_replica, (envelope, sub), "adopt_decode")
                    while True:
                        try:
                            item = await loop.run_in_executor(
                                self._executor, _next_item, frames)
                        except (ray_tpu.exceptions.ActorDiedError,
                                ray_tpu.exceptions.ActorUnavailableError
                                ) as e:
                            self._on_replica_death(dec_key, e)
                            avoid_dec.add(dec_key)
                            rerouted = True
                            break
                        if item is _END or (
                                not isinstance(item, dict)):
                            yield self._final(emitted, first_t, t0,
                                              attempts, dec_key)
                            return
                        if item.get("handoff_lost"):
                            # exporter (or its store) died before the
                            # decode replica mapped the pages: the
                            # envelope's refs — and their directory
                            # entries — are dangling
                            with self._lock:
                                self.counters["handoffs_lost"] += 1
                            await loop.run_in_executor(
                                self._executor, self._drop_dangling,
                                envelope)
                            rerouted = True
                            break
                        if item.get("status") == 429:
                            with self._lock:
                                self.counters["replica_shed"] += 1
                            avoid_dec.add(dec_key)
                            rerouted = True
                            break
                        if item.get("done"):
                            out = self._final(emitted, first_t, t0,
                                              attempts, dec_key)
                            if item.get("error"):
                                out["error"] = item["error"]
                            yield out
                            return
                        toks = item.get("tokens", [])
                        if toks:
                            if first_t is None:
                                first_t = time.time()
                                self._m_ttft.observe(first_t - t0)
                            if not handoff_seen:
                                handoff_seen = True
                                self._m_handoff_s.observe(
                                    time.time() - t_env)
                            emitted.extend(toks)
                            yield {"tokens": toks}
                finally:
                    with self._lock:
                        if self._inflight.get(dec_key, 0) > 0:
                            self._inflight[dec_key] -= 1
                    # ack EVERY attempt's handoff — completed, rerouted,
                    # or abandoned by the client — so the prefill-side
                    # pins never outlive the attempt
                    self._ack(pf_replica, envelope["handoff_id"])
                if not rerouted:
                    return
        finally:
            with self._lock:
                self._total_inflight = max(self._total_inflight - 1, 0)
                self._m_inflight.set(self._total_inflight)

    def _on_prefill_death(self, key: str, err) -> None:
        """Prefill replica died mid-call: evict it from the prefill
        pool's shared replica view and account the re-route."""
        rt = self._pf_handle._get_router()
        rt.evict(getattr(err, "actor_id", None) or key)
        with self._lock:
            self._pf_stats.pop(key, None)
            self.counters["prefill_reroutes"] += 1
        self._m_reroutes.inc()

    # ---- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        with self._lock:
            out["prefill_inflight"] = dict(self._pf_inflight)
            out["prefill_replica_stats"] = {
                k: {kk: vv for kk, vv in v.items()
                    if not kk.startswith("_")}
                for k, v in self._pf_stats.items()}
        return out
