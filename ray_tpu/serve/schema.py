"""Declarative serve config (REST/CLI schema).

Reference: python/ray/serve/schema.py — ServeDeploySchema: a config file
listing applications (import_path + route_prefix + per-deployment
overrides) that `serve deploy` applies. Here the config is JSON (YAML also
accepted when pyyaml is importable) and `apply_config` builds and runs each
application from its import path.

Config shape:
    {
      "applications": [
        {
          "name": "app1",
          "route_prefix": "/app1",
          "import_path": "mypkg.mymodule:app",
          "deployments": [
            {"name": "Model", "num_replicas": 2,
             "user_config": {...}, "autoscaling_config": {...}}
          ]
        }
      ]
    }
"""

from __future__ import annotations

import importlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    user_config: Any = None
    autoscaling_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None


@dataclass
class ApplicationSchema:
    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = None
    deployments: List[DeploymentSchema] = field(default_factory=list)


@dataclass
class ServeDeploySchema:
    applications: List[ApplicationSchema] = field(default_factory=list)
    http_host: str = "127.0.0.1"
    http_port: int = 8000

    @classmethod
    def parse(cls, data: dict) -> "ServeDeploySchema":
        apps = []
        for a in data.get("applications", []):
            deps = [DeploymentSchema(**d) for d in a.get("deployments", [])]
            apps.append(ApplicationSchema(
                import_path=a["import_path"],
                name=a.get("name", "default"),
                route_prefix=a.get("route_prefix"),
                deployments=deps))
        http = data.get("http_options", {})
        return cls(applications=apps,
                   http_host=http.get("host", "127.0.0.1"),
                   http_port=http.get("port", 8000))

    @classmethod
    def from_file(cls, path: str) -> "ServeDeploySchema":
        with open(path) as f:
            text = f.read()
        try:
            data = json.loads(text)
        except json.JSONDecodeError:
            import yaml  # optional; JSON is the native format

            data = yaml.safe_load(text)
        return cls.parse(data)


def _import_app(import_path: str):
    module_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path must be 'module:attr', got {import_path!r}")
    mod = importlib.import_module(module_name)
    return getattr(mod, attr)


def apply_config(schema: ServeDeploySchema, *, start_http: bool = True
                 ) -> Dict[str, Any]:
    """Build + run every application in the config; returns route→port info."""
    from ray_tpu import serve

    port = None
    if start_http:
        port = serve.start(http_host=schema.http_host,
                           http_port=schema.http_port)
    routes = {}
    for app_schema in schema.applications:
        app = _import_app(app_schema.import_path)
        # per-deployment overrides by name
        overrides = {d.name: d for d in app_schema.deployments}
        for dep in app.deployments:
            ov = overrides.get(dep.name)
            if ov is None:
                continue
            if ov.num_replicas is not None:
                dep.num_replicas = ov.num_replicas
            if ov.max_concurrent_queries is not None:
                dep.max_concurrent_queries = ov.max_concurrent_queries
            if ov.user_config is not None:
                dep.user_config = ov.user_config
            if ov.autoscaling_config is not None:
                dep.autoscaling_config = ov.autoscaling_config
            if ov.ray_actor_options is not None:
                dep.ray_actor_options = ov.ray_actor_options
        serve.run(app, route_prefix=app_schema.route_prefix)
        if app_schema.route_prefix:
            routes[app_schema.route_prefix] = app.ingress.name
    return {"http_port": port, "routes": routes}
