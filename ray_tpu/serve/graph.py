"""Serve deployment graphs: model composition as a DAG + DAGDriver ingress.

Reference: python/ray/serve/deployment_graph_build.py (walk a DAG of bound
deployments, emit the deployment list) and serve/drivers.py (DAGDriver —
an ingress deployment that executes the graph per request, with an
optional http adapter). Authoring mirrors the reference idiom:

    with InputNode() as inp:
        a = Preprocess.bind()
        b = Model.bind()
        out = b.predict.bind(a.transform.bind(inp))
    app = build_app(out)
    handle = serve.run(app)

The compiled graph ships to the DAGDriver replica as a pure-data spec
(deployment NAMES, not objects); the driver resolves DeploymentHandles
lazily and re-executes the spec per request. Independent branches are
submitted as soon as their inputs materialize; each stage is an async
handle call.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.serve.api import Application, Deployment, deployment
from ray_tpu.serve.handle import DeploymentHandle


class DeploymentMethodNode:
    """`app.method.bind(*args)` — one graph stage calling a deployment
    method; args may contain other nodes, InputNode markers, or literals
    (ref: serve/deployment_method_node.py)."""

    def __init__(self, app: Application, method: str, args: tuple,
                 kwargs: dict):
        self.app = app
        self.method = method
        self.args = args
        self.kwargs = kwargs


class _GraphMethod:
    def __init__(self, app: Application, name: str):
        self._app = app
        self._name = name

    def bind(self, *args, **kwargs) -> DeploymentMethodNode:
        return DeploymentMethodNode(self._app, self._name, args, kwargs)


class GraphInput:
    """Request-input placeholder (ref: dag InputNode used in serve graphs).
    `with InputNode() as inp:` — index/attr access addresses structured
    inputs."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key):
        return _GraphInputAttr(key)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _GraphInputAttr(name)


class _GraphInputAttr:
    def __init__(self, key):
        self.key = key


def _compile(node: Any, apps: Dict[str, Application]) -> dict:
    """Node -> pure-data spec; collects referenced applications."""
    if isinstance(node, DeploymentMethodNode):
        name = node.app.ingress.name
        prev = apps.setdefault(name, node.app)
        if prev.ingress is not node.app.ingress:
            raise ValueError(
                f"two distinct bound deployments share the name {name!r}; "
                "give one a .options(name=...) — merging them would route "
                "both graph stages to whichever deployed first")
        return {
            "type": "call",
            # identity key: a node shared by two downstream stages must
            # execute ONCE per request (ref: DAG nodes are walked with a
            # seen-set), even though it compiles into both branches
            "id": id(node),
            "deployment": name,
            "method": node.method,
            "args": [_compile(a, apps) for a in node.args],
            "kwargs": {k: _compile(v, apps)
                       for k, v in node.kwargs.items()},
        }
    if isinstance(node, Application):
        # a bare bound deployment as a stage input -> its handle
        name = node.ingress.name
        prev = apps.setdefault(name, node)
        if prev.ingress is not node.ingress:
            raise ValueError(
                f"two distinct bound deployments share the name {name!r}; "
                "give one a .options(name=...)")
        return {"type": "handle", "deployment": name}
    if isinstance(node, GraphInput):
        return {"type": "input"}
    if isinstance(node, _GraphInputAttr):
        return {"type": "input_attr", "key": node.key}
    if isinstance(node, (list, tuple)):
        return {"type": "list" if isinstance(node, list) else "tuple",
                "items": [_compile(x, apps) for x in node]}
    if isinstance(node, dict):
        return {"type": "dict",
                "items": {k: _compile(v, apps) for k, v in node.items()}}
    return {"type": "const", "value": node}


class DAGDriverImpl:
    """Ingress callable executing a compiled graph spec per request
    (ref: drivers.py DAGDriver.predict / __call__)."""

    def __init__(self, spec: dict, http_adapter=None):
        self.spec = spec
        self.http_adapter = http_adapter
        self._handles: Dict[str, DeploymentHandle] = {}

    def _handle(self, name: str) -> DeploymentHandle:
        if name not in self._handles:
            self._handles[name] = DeploymentHandle(name)
        return self._handles[name]

    def _run(self, spec: dict, request, memo: dict):
        t = spec["type"]
        if t == "const":
            return spec["value"]
        if t == "input":
            return request
        if t == "input_attr":
            key = spec["key"]
            if isinstance(request, dict):
                return request[key]
            if isinstance(key, int):
                return request[key]
            return getattr(request, key)
        if t == "handle":
            return self._handle(spec["deployment"])
        if t in ("list", "tuple"):
            out = self._fan(spec["items"], request, memo)
            return out if t == "list" else tuple(out)
        if t == "dict":
            keys = list(spec["items"])
            vals = self._fan([spec["items"][k] for k in keys], request, memo)
            return dict(zip(keys, vals))
        if t == "call":
            return self._call_once(spec, request, memo)
        raise ValueError(f"bad graph node type {t!r}")

    def _call_once(self, spec: dict, request, memo: dict):
        """Execute a call node exactly once per request even when it is
        shared by several downstream stages; concurrent consumers wait on
        the first executor's Future."""
        from concurrent.futures import Future

        node_id = spec["id"]
        with memo["lock"]:
            fut = memo.get(node_id)
            if fut is None:
                fut = memo[node_id] = Future()
                owner = True
            else:
                owner = False
        if not owner:
            return fut.result()
        try:
            import ray_tpu

            args = self._fan(spec["args"], request, memo)
            kwargs = dict(zip(
                spec["kwargs"],
                self._fan(list(spec["kwargs"].values()), request, memo)))
            h = self._handle(spec["deployment"])
            ref = h.method(spec["method"]).remote(*args, **kwargs) \
                if spec["method"] != "__call__" else h.remote(*args, **kwargs)
            out = ray_tpu.get(ref)
        except BaseException as e:
            fut.set_exception(e)
            raise
        fut.set_result(out)
        return out

    def _fan(self, specs, request, memo: dict):
        """Evaluate sibling subtrees concurrently so independent branches
        of a diamond overlap (each branch blocks on its own gets). One
        BRANCHING sibling runs inline; the others get dedicated threads —
        a bounded shared pool here can deadlock (threads blocked in
        result() on children that queue behind them) at depth under
        load, and thread spawn is cheap next to a handle round-trip."""
        import threading

        branch_idx = [i for i, s in enumerate(specs)
                      if s["type"] in ("call", "list", "tuple", "dict")]
        if len(branch_idx) < 2:
            return [self._run(s, request, memo) for s in specs]
        inline_i = branch_idx[-1]
        out: list = [None] * len(specs)
        errs: list = []
        threads = []
        for i in branch_idx[:-1]:
            def work(i=i):
                try:
                    out[i] = self._run(specs[i], request, memo)
                except BaseException as e:  # re-raised on the caller
                    errs.append(e)
            t = threading.Thread(target=work, daemon=True)
            t.start()
            threads.append(t)
        for i, s in enumerate(specs):
            if i == inline_i or i not in branch_idx:
                out[i] = self._run(s, request, memo)
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return out

    def predict(self, request):
        import threading

        return self._run(self.spec, request, {"lock": threading.Lock()})

    def __call__(self, request):
        if self.http_adapter is not None:
            request = self.http_adapter(request)
        return self.predict(request)


def build_app(root: DeploymentMethodNode, *, name: str = "DAGDriver",
              http_adapter=None, num_replicas: int = 1) -> Application:
    """Compile a deployment graph into a runnable Application whose
    ingress is a DAGDriver (ref: deployment_graph_build.py build +
    drivers.py DAGDriver.bind)."""
    apps: Dict[str, Application] = {}
    spec = _compile(root, apps)
    driver = deployment(DAGDriverImpl, name=name,
                        num_replicas=num_replicas)
    driver_app = driver.bind(spec, http_adapter)
    merged: List[Deployment] = list(driver_app.deployments)
    seen: Dict[str, Deployment] = {d.name: d for d in merged}
    for app in apps.values():
        for d in app.deployments:
            prev = seen.get(d.name)
            if prev is None:
                seen[d.name] = d
                merged.append(d)
            elif prev is not d:
                raise ValueError(
                    f"two distinct bound deployments share the name "
                    f"{d.name!r}; give one a .options(name=...)")
    return Application(merged, driver_app.ingress)


# authoring alias matching the reference's import name
InputNode = GraphInput
