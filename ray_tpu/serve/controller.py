"""ServeController + Replica actors.

Reference: python/ray/serve/controller.py:74 (checkpointed controller state
machine), _private/deployment_state.py:1097 (replica FSM, rolling updates,
_scale_deployment_replicas:1537), _private/replica.py, autoscaling on
replica queue metrics (_private/autoscaling_policy.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


@ray_tpu.remote
class Replica:
    """Wraps one instance of the user's deployment callable. Requests enter
    via handle_request; an async-capable wrapper lets @serve.batch and
    async __call__ work; queue depth is tracked for autoscaling."""

    def __init__(self, import_blob: bytes, init_args, init_kwargs,
                 user_config=None):
        import cloudpickle

        cls_or_fn = cloudpickle.loads(import_blob)
        if isinstance(cls_or_fn, type):
            self.instance = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self.instance = cls_or_fn
        self.inflight = 0
        if user_config is not None and hasattr(self.instance,
                                               "reconfigure"):
            self.instance.reconfigure(user_config)

    async def handle_request(self, method: str, args, kwargs,
                             context: dict | None = None):
        self.inflight += 1
        try:
            if context and "multiplexed_model_id" in context:
                from ray_tpu.serve.multiplex import _set_multiplexed_model_id

                _set_multiplexed_model_id(context["multiplexed_model_id"])
            import asyncio
            import inspect

            fn = getattr(self.instance, method)
            if inspect.iscoroutinefunction(fn):
                out = await fn(*args, **kwargs)
            else:
                # Sync handlers run on an executor thread (ref:
                # _private/replica.py runs sync callables off the event
                # loop) so they may issue blocking runtime calls — e.g.
                # a composed deployment ray_tpu.get()-ing a child handle.
                out = await asyncio.to_thread(fn, *args, **kwargs)
                if asyncio.iscoroutine(out):
                    out = await out
            return out
        finally:
            self.inflight -= 1

    @ray_tpu.method(num_returns="streaming")
    async def handle_request_streaming(self, method: str, args, kwargs,
                                       context: dict | None = None):
        """Streaming twin of handle_request (ref: the proxy's
        obj-ref-generator calls for response streaming): drives the user
        method — async generator, sync generator, or iterable-returning —
        and yields each item as a stream element."""
        self.inflight += 1
        try:
            if context and "multiplexed_model_id" in context:
                from ray_tpu.serve.multiplex import _set_multiplexed_model_id

                _set_multiplexed_model_id(context["multiplexed_model_id"])
            import asyncio
            import inspect

            fn = getattr(self.instance, method)
            if inspect.isasyncgenfunction(fn):
                async for item in fn(*args, **kwargs):
                    yield item
                return
            if inspect.iscoroutinefunction(fn):
                out = await fn(*args, **kwargs)
            else:
                out = await asyncio.to_thread(fn, *args, **kwargs)
            if inspect.isgenerator(out) or (
                    hasattr(out, "__iter__")
                    and not isinstance(out, (str, bytes, dict, list,
                                             tuple))):
                loop = asyncio.get_running_loop()
                _end = object()
                it = iter(out)
                while True:   # sync generator: step off-loop per item
                    item = await loop.run_in_executor(None, next, it, _end)
                    if item is _end:
                        return
                    yield item
            else:
                yield out
        finally:
            self.inflight -= 1

    def queue_len(self) -> int:
        return self.inflight

    def reconfigure(self, user_config):
        if hasattr(self.instance, "reconfigure"):
            self.instance.reconfigure(user_config)
        return True


@ray_tpu.remote
class ServeController:
    """Deployment table + reconcile/autoscale thread
    (ref: controller.py run_control_loop)."""

    def __init__(self):
        self.deployments: Dict[str, dict] = {}
        self.routes: Dict[str, str] = {}   # route_prefix -> ingress deployment
        self._lock = threading.Lock()
        self._stop = False
        self._thread = threading.Thread(target=self._control_loop, daemon=True)
        self._thread.start()

    # ---- API ----------------------------------------------------------------

    def deploy(self, name: str, import_blob: bytes, init_args, init_kwargs,
               config: dict) -> bool:
        with self._lock:
            old = self.deployments.get(name)
            self.deployments[name] = {
                "blob": import_blob, "args": init_args,
                "kwargs": init_kwargs or {}, "config": dict(config),
                "replicas": old["replicas"] if old else [],
                "version": (old["version"] + 1) if old else 0,
            }
        self._reconcile(name, rolling=old is not None)
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            d = self.deployments.pop(name, None)
            self.routes = {p: n for p, n in self.routes.items() if n != name}
        if d:
            for r in d["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        return True

    def get_replicas(self, name: str) -> List[Any]:
        d = self.deployments.get(name)
        return list(d["replicas"]) if d else []

    def set_route(self, route_prefix: str, deployment: str) -> bool:
        with self._lock:
            self.routes[route_prefix] = deployment
        return True

    def get_routes(self) -> Dict[str, str]:
        return dict(self.routes)

    def list_deployments(self) -> Dict[str, dict]:
        out = {}
        for name, d in self.deployments.items():
            out[name] = {"num_replicas": len(d["replicas"]),
                         "config": d["config"], "version": d["version"]}
        return out

    def ping(self) -> str:
        return "pong"

    # ---- reconcile ----------------------------------------------------------

    def _make_replica(self, d: dict):
        cfg = d["config"]
        opts = {"max_concurrency": cfg.get("max_concurrent_queries", 100)}
        if cfg.get("ray_actor_options"):
            opts.update(cfg["ray_actor_options"])
        return Replica.options(**opts).remote(
            d["blob"], d["args"], d["kwargs"], cfg.get("user_config"))

    def _reconcile(self, name: str, rolling: bool = False):
        with self._lock:
            d = self.deployments.get(name)
            if d is None:
                return
            target = int(d["config"].get("num_replicas", 1))
            replicas = d["replicas"]
        if rolling:
            # rolling update: replace one at a time (ref:
            # deployment_state.py rolling update path)
            new = []
            for r in replicas:
                nr = self._make_replica(d)
                ray_tpu.get(nr.queue_len.remote())     # wait ready
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
                new.append(nr)
            replicas = new
        while len(replicas) < target:
            replicas.append(self._make_replica(d))
        while len(replicas) > target:
            r = replicas.pop()
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        with self._lock:
            if name in self.deployments:
                self.deployments[name]["replicas"] = replicas

    def _control_loop(self):
        """Autoscaling on queue depth (ref: autoscaling_policy.py — target
        ongoing requests per replica) + dead-replica replacement."""
        while not self._stop:
            time.sleep(1.0)
            for name in list(self.deployments):
                d = self.deployments.get(name)
                if d is None:
                    continue
                auto = d["config"].get("autoscaling_config")
                # replace dead replicas
                alive = []
                for r in d["replicas"]:
                    try:
                        ray_tpu.get(r.queue_len.remote(), timeout=5)
                        alive.append(r)
                    except Exception:
                        pass
                if len(alive) != len(d["replicas"]):
                    with self._lock:
                        d["replicas"] = alive
                    self._reconcile(name)
                    continue
                if not auto:
                    continue
                try:
                    qs = ray_tpu.get([r.queue_len.remote()
                                      for r in d["replicas"]], timeout=5)
                except Exception:
                    continue
                total = sum(qs)
                per = auto.get("target_num_ongoing_requests_per_replica", 2)
                want = max(auto.get("min_replicas", 1),
                           min(auto.get("max_replicas", 4),
                               (total + per - 1) // per or 1))
                if want != len(d["replicas"]):
                    with self._lock:
                        d["config"]["num_replicas"] = want
                    self._reconcile(name)
