"""ServeController + Replica actors.

Reference: python/ray/serve/controller.py:74 (checkpointed controller state
machine), _private/deployment_state.py:1097 (replica FSM, rolling updates,
_scale_deployment_replicas:1537), _private/replica.py, long-poll
control-plane push (_private/long_poll.py:69,187), autoscaling on replica
queue metrics with look-back + up/down delays
(_private/autoscaling_policy.py).

Fault tolerance: the controller persists its deployment table (blobs,
configs, routes, versions, replica ACTOR NAMES) to GCS KV on every
mutation and runs with max_restarts=-1. Replicas are named actors, so a
restarted controller re-adopts the live ones by name — no redeploys, no
dropped replicas (the reference recovers the same way from its KV
checkpoints, controller.py:74-79).
"""

from __future__ import annotations

import math
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

STATE_KEY = b"controller_state"
_KV_NS = "serve"


@ray_tpu.remote
class Replica:
    """Wraps one instance of the user's deployment callable. Requests enter
    via handle_request; an async-capable wrapper lets @serve.batch and
    async __call__ work; queue depth is tracked for autoscaling."""

    def __init__(self, import_blob: bytes, init_args, init_kwargs,
                 user_config=None):
        import cloudpickle

        cls_or_fn = cloudpickle.loads(import_blob)
        if isinstance(cls_or_fn, type):
            self.instance = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self.instance = cls_or_fn
        self.inflight = 0
        if user_config is not None and hasattr(self.instance,
                                               "reconfigure"):
            self.instance.reconfigure(user_config)

    async def handle_request(self, method: str, args, kwargs,
                             context: dict | None = None):
        self.inflight += 1
        try:
            if context:
                from ray_tpu.serve.multiplex import (_set_multiplexed_model_id,
                                                     _set_request_tenant)

                if "multiplexed_model_id" in context:
                    _set_multiplexed_model_id(context["multiplexed_model_id"])
                if "tenant" in context:
                    _set_request_tenant(context["tenant"])
            import asyncio
            import inspect

            fn = getattr(self.instance, method)
            if inspect.iscoroutinefunction(fn):
                out = await fn(*args, **kwargs)
            else:
                # Sync handlers run on an executor thread (ref:
                # _private/replica.py runs sync callables off the event
                # loop) so they may issue blocking runtime calls — e.g.
                # a composed deployment ray_tpu.get()-ing a child handle.
                out = await asyncio.to_thread(fn, *args, **kwargs)
                if asyncio.iscoroutine(out):
                    out = await out
            return out
        finally:
            self.inflight -= 1

    @ray_tpu.method(num_returns="streaming")
    async def handle_request_streaming(self, method: str, args, kwargs,
                                       context: dict | None = None):
        """Streaming twin of handle_request (ref: the proxy's
        obj-ref-generator calls for response streaming): drives the user
        method — async generator, sync generator, or iterable-returning —
        and yields each item as a stream element."""
        self.inflight += 1
        try:
            if context:
                from ray_tpu.serve.multiplex import (_set_multiplexed_model_id,
                                                     _set_request_tenant)

                if "multiplexed_model_id" in context:
                    _set_multiplexed_model_id(context["multiplexed_model_id"])
                if "tenant" in context:
                    _set_request_tenant(context["tenant"])
            import asyncio
            import inspect

            fn = getattr(self.instance, method)
            if inspect.isasyncgenfunction(fn):
                async for item in fn(*args, **kwargs):
                    yield item
                return
            if inspect.iscoroutinefunction(fn):
                out = await fn(*args, **kwargs)
            else:
                out = await asyncio.to_thread(fn, *args, **kwargs)
            if inspect.isgenerator(out) or (
                    hasattr(out, "__iter__")
                    and not isinstance(out, (str, bytes, dict, list,
                                             tuple))):
                loop = asyncio.get_running_loop()
                _end = object()
                it = iter(out)
                while True:   # sync generator: step off-loop per item
                    item = await loop.run_in_executor(None, next, it, _end)
                    if item is _end:
                        return
                    yield item
            else:
                yield out
        finally:
            self.inflight -= 1

    def queue_len(self) -> int:
        """RPC in-flight count, plus the instance's own backlog when it
        exposes one (LLMServer.queue_len: engine pending + active slots).
        A streaming LLM replica parks few RPCs but can hold many
        generations — autoscaling and drain must see those too."""
        n = self.inflight
        ql = getattr(self.instance, "queue_len", None)
        if callable(ql):
            try:
                n += int(ql())
            except Exception:
                pass
        return n

    def drain(self) -> bool:
        """Tell the instance to stop accepting new work (scale-down
        protocol); returns immediately, in-flight work keeps running."""
        fn = getattr(self.instance, "drain", None)
        if callable(fn):
            try:
                fn()
            except Exception:
                pass
        return True

    def reconfigure(self, user_config):
        if hasattr(self.instance, "reconfigure"):
            self.instance.reconfigure(user_config)
        return True


def _kv_put(key: bytes, value: bytes):
    from ray_tpu.core import runtime as _rt

    _rt.get_runtime().kv_put(_KV_NS, key, value)


def _kv_get(key: bytes) -> Optional[bytes]:
    from ray_tpu.core import runtime as _rt

    return _rt.get_runtime().kv_get(_KV_NS, key)


@ray_tpu.remote
class ServeController:
    """Deployment table + reconcile/autoscale thread
    (ref: controller.py run_control_loop).

    In-memory `deployments[name]` holds live actor handles in "replicas"
    and their names in "replica_names" (parallel lists); the persisted
    checkpoint stores everything EXCEPT the handles."""

    def __init__(self):
        self.deployments: Dict[str, dict] = {}
        self.routes: Dict[str, str] = {}   # route_prefix -> ingress deployment
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # long-poll channels (ref: long_poll.py LongPollHost): generation
        # per key; waiters block on the condition until the key's gen
        # advances past theirs.
        self._gen: Dict[str, int] = {}
        self._poll_cond = threading.Condition()
        # autoscaling look-back samples: name -> list[(ts, total_queue)]
        self._qhist: Dict[str, List[tuple]] = {}
        # pending scale decision: name -> (direction, first_seen_ts, want)
        self._pending_scale: Dict[str, tuple] = {}
        # router-reported load: name -> {reporter: (ts, load)}. LLM
        # routers push their local queue depth here so autoscaling sees
        # demand that was SHED before reaching any replica's queue.
        self._ext_load: Dict[str, Dict[str, tuple]] = {}
        # per-model external load: name -> {reporter: (ts, {model: load})}
        self._ext_mload: Dict[str, Dict[str, tuple]] = {}
        # per-model autoscaling state (multiplexed deployments):
        # look-back samples keyed (name, model), pending-decision delays,
        # in-flight scale ops (one per model at a time), and the last
        # decision table exposed via model_status()
        self._mhist: Dict[tuple, List[tuple]] = {}
        self._pending_mscale: Dict[tuple, tuple] = {}
        self._model_ops: set = set()
        self._model_table: Dict[str, dict] = {}
        self._restore()
        self._thread = threading.Thread(target=self._control_loop, daemon=True)
        self._thread.start()

    # ---- persistence (ref: controller.py:74 checkpointed state) ------------

    def _save(self):
        import cloudpickle

        with self._lock:
            snap = {
                "routes": dict(self.routes),
                "deployments": {
                    name: {k: d[k] for k in
                           ("blob", "args", "kwargs", "config", "version",
                            "replica_names")}
                    for name, d in self.deployments.items()
                },
            }
        try:
            _kv_put(STATE_KEY, cloudpickle.dumps(snap))
        except Exception:
            pass  # KV down: state is still live in-memory; next save retries

    def _restore(self):
        import cloudpickle

        try:
            raw = _kv_get(STATE_KEY)
        except Exception:
            raw = None
        if not raw:
            return
        snap = cloudpickle.loads(raw)
        self.routes = dict(snap.get("routes", {}))
        for name, d in snap.get("deployments", {}).items():
            replicas, names = [], []
            for rn in d.get("replica_names", []):
                # re-adopt replicas that survived the controller crash —
                # zero redeploys for live actors
                try:
                    h = ray_tpu.get_actor(rn, namespace=_KV_NS)
                    ray_tpu.get(h.queue_len.remote(), timeout=5)
                    replicas.append(h)
                    names.append(rn)
                except Exception:
                    pass
            self.deployments[name] = {**d, "replicas": replicas,
                                      "replica_names": names}
        # top up any deployment that lost replicas while we were down
        for name in list(self.deployments):
            self._reconcile(name)

    # ---- long-poll push (ref: long_poll.py:187) ----------------------------

    def _bump(self, key: str):
        with self._poll_cond:
            self._gen[key] = self._gen.get(key, 0) + 1
            self._poll_cond.notify_all()

    def _snapshot(self, key: str):
        if key == "routes":
            return dict(self.routes)
        if key.startswith("replicas:"):
            return self.get_replicas(key.split(":", 1)[1])
        return None

    def long_poll(self, key: str, last_gen: int, timeout: float = 10.0):
        """Block until channel `key`'s generation advances past last_gen
        (or timeout); returns {"gen": g, "value": snapshot}. Routers and
        proxies keep one of these pending instead of polling on a timer —
        a config/replica change propagates in one RPC round trip."""
        deadline = time.time() + timeout
        with self._poll_cond:
            while self._gen.get(key, 0) <= last_gen:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._poll_cond.wait(remaining)
            g = self._gen.get(key, 0)
        return {"gen": g, "value": self._snapshot(key)}

    # ---- API ----------------------------------------------------------------

    def deploy(self, name: str, import_blob: bytes, init_args, init_kwargs,
               config: dict) -> bool:
        with self._lock:
            old = self.deployments.get(name)
            self.deployments[name] = {
                "blob": import_blob, "args": init_args,
                "kwargs": init_kwargs or {}, "config": dict(config),
                "replicas": old["replicas"] if old else [],
                "replica_names": old["replica_names"] if old else [],
                "version": (old["version"] + 1) if old else 0,
            }
        self._reconcile(name, rolling=old is not None)
        self._save()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            d = self.deployments.pop(name, None)
            self.routes = {p: n for p, n in self.routes.items() if n != name}
        if d:
            for r in d["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        self._save()
        self._bump(f"replicas:{name}")
        self._bump("routes")
        return True

    def get_replicas(self, name: str) -> List[Any]:
        d = self.deployments.get(name)
        return list(d["replicas"]) if d else []

    def set_route(self, route_prefix: str, deployment: str) -> bool:
        with self._lock:
            self.routes[route_prefix] = deployment
        self._save()
        self._bump("routes")
        return True

    def get_routes(self) -> Dict[str, str]:
        return dict(self.routes)

    def list_deployments(self) -> Dict[str, dict]:
        out = {}
        for name, d in self.deployments.items():
            out[name] = {"num_replicas": len(d["replicas"]),
                         "config": d["config"], "version": d["version"]}
        return out

    def report_load(self, name: str, reporter: str, load: float,
                    model_load: Optional[Dict[str, float]] = None) -> bool:
        """Routers push their OWN queue depth (requests admitted by the
        router but not yet placed on a replica). Folded into the
        autoscale total each control tick; stale reporters (a dead
        router) age out after 10 s so they cannot pin the fleet up.
        model_load, when given, is the router's per-model split of that
        depth — the per-model autoscaler's demand signal."""
        with self._lock:
            self._ext_load.setdefault(name, {})[reporter] = (
                time.time(), float(load))
            if model_load is not None:
                self._ext_mload.setdefault(name, {})[reporter] = (
                    time.time(), {str(m): float(v)
                                  for m, v in model_load.items()})
        return True

    def _ext_load_total(self, name: str) -> float:
        now = time.time()
        with self._lock:
            per = self._ext_load.get(name, {})
            stale = [k for k, (ts, _) in per.items() if now - ts > 10.0]
            for k in stale:
                del per[k]
            return sum(load for _, load in per.values())

    def _ext_model_load(self, name: str) -> Dict[str, float]:
        """Aged, summed per-model router demand."""
        now = time.time()
        out: Dict[str, float] = {}
        with self._lock:
            per = self._ext_mload.get(name, {})
            stale = [k for k, (ts, _) in per.items() if now - ts > 10.0]
            for k in stale:
                del per[k]
            for _, (_, d) in per.items():
                for m, v in d.items():
                    out[m] = out.get(m, 0.0) + v
        return out

    def model_status(self, name: str) -> dict:
        """Last per-model autoscale decision table (tests/bench)."""
        return dict(self._model_table.get(name, {}))

    def ping(self) -> str:
        return "pong"

    def shutdown(self) -> bool:
        """Stop the control loop before the actor is killed. Actors can
        be lane-packed into shared worker processes, so a daemon thread
        left spinning outlives its actor and keeps health-probing dead
        replicas forever."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        # wake any parked long-pollers so their handles return instead
        # of riding out the full poll timeout against a dead controller
        with self._poll_cond:
            self._poll_cond.notify_all()
        return not self._thread.is_alive()

    # ---- reconcile ----------------------------------------------------------

    def _make_replica(self, name: str, d: dict):
        cfg = d["config"]
        opts = {"max_concurrency": cfg.get("max_concurrent_queries", 100)}
        if cfg.get("ray_actor_options"):
            opts.update(cfg["ray_actor_options"])
        # named so a restarted controller can re-adopt it (see _restore)
        rname = f"_serve_rep_{name}_{uuid.uuid4().hex[:8]}"
        h = Replica.options(name=rname, namespace=_KV_NS, **opts).remote(
            d["blob"], d["args"], d["kwargs"], cfg.get("user_config"))
        return h, rname

    def _reconcile(self, name: str, rolling: bool = False):
        with self._lock:
            d = self.deployments.get(name)
            if d is None:
                return
            target = int(d["config"].get("num_replicas", 1))
            health_timeout = float(
                d["config"].get("health_check_timeout_s", 30.0))
            replicas = list(d["replicas"])
            names = list(d["replica_names"])
        if rolling:
            # rolling update: replace one at a time; a new replica that
            # fails its readiness deadline ABORTS the update, keeping the
            # old replicas serving (ref: deployment_state.py rolling
            # update + health deadline)
            new, new_names = [], []
            aborted = False
            for i, r in enumerate(replicas):
                nr, nn = self._make_replica(name, d)
                try:
                    ray_tpu.get(nr.queue_len.remote(),
                                timeout=health_timeout)   # wait ready
                except Exception:
                    try:
                        ray_tpu.kill(nr)
                    except Exception:
                        pass
                    new.extend(replicas[i:])
                    new_names.extend(names[i:])
                    aborted = True
                    break
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
                new.append(nr)
                new_names.append(nn)
            replicas, names = new, new_names
            if aborted:
                with self._lock:
                    if name in self.deployments:
                        self.deployments[name]["replicas"] = replicas
                        self.deployments[name]["replica_names"] = names
                        self.deployments[name]["last_error"] = (
                            "rolling update aborted: new replica failed "
                            f"readiness within {health_timeout}s")
                self._save()
                self._bump(f"replicas:{name}")
                return
        # Scale-up: start all missing replicas concurrently, then
        # readiness-gate EVERY entry to the serving set, not just rolling
        # swaps — after an aborted update the table may hold a blob whose
        # __init__ fails, and scale-up must not hand routers a broken
        # replica. Failures are killed and surfaced via last_error; the
        # control loop retries next tick (ref: deployment_state keeps
        # retrying and surfaces UNHEALTHY, it does not roll back).
        started = [self._make_replica(name, d)
                   for _ in range(max(target - len(replicas), 0))]
        for h, rn in started:
            try:
                ray_tpu.get(h.queue_len.remote(), timeout=health_timeout)
            except Exception as err:   # noqa: BLE001 — any startup failure
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
                with self._lock:
                    if name in self.deployments:
                        self.deployments[name]["last_error"] = (
                            f"replica failed readiness: {err}")
                continue
            replicas.append(h)
            names.append(rn)
        # Scale-down drains instead of killing: unpublish FIRST (the
        # table update + bump below pushes the shrunk set to every
        # router long-poll, so no new requests target the retiring
        # replicas), then a background thread waits for their in-flight
        # work — mid-stream generations included — before the kill.
        retiring = []
        while len(replicas) > target:
            retiring.append(replicas.pop())
            names.pop()
        with self._lock:
            if name in self.deployments:
                self.deployments[name]["replicas"] = replicas
                self.deployments[name]["replica_names"] = names
        self._save()
        self._bump(f"replicas:{name}")
        if retiring:
            threading.Thread(target=self._drain_then_kill,
                             args=(retiring,), daemon=True).start()

    def _drain_then_kill(self, retiring: List[Any]):
        """Scale-down grace: tell each retiring replica to stop
        admitting (Replica.drain -> instance drain), poll queue_len to 0
        under serve_drain_timeout_s, then kill. A replica that cannot
        drain in time is killed anyway — the bound keeps scale-down from
        hanging behind a wedged stream."""
        from ray_tpu.core.config import GLOBAL_CONFIG

        deadline = time.time() + GLOBAL_CONFIG.serve_drain_timeout_s
        for r in retiring:
            try:
                ray_tpu.get(r.drain.remote(), timeout=5)
            except Exception:
                pass   # dead/unreachable: the kill below still runs
        pending = list(retiring)
        while pending and time.time() < deadline \
                and not self._stop.is_set():
            still = []
            for r in pending:
                try:
                    if ray_tpu.get(r.queue_len.remote(), timeout=5) > 0:
                        still.append(r)
                except Exception:
                    pass   # already dead: drained by definition
            pending = still
            if pending:
                self._stop.wait(0.2)
        for r in retiring:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    # ---- autoscaling (ref: autoscaling_policy.py) --------------------------

    def _autoscale_decision(self, name: str, d: dict, total: int):
        """Look-back averaged queue depth + upscale/downscale delays.
        Returns the target replica count to apply now, or None."""
        auto = d["config"].get("autoscaling_config")
        if not auto:
            return None
        now = time.time()
        look_back = float(auto.get("look_back_period_s", 30.0))
        hist = self._qhist.setdefault(name, [])
        hist.append((now, total))
        while hist and hist[0][0] < now - look_back:
            hist.pop(0)
        avg = sum(q for _, q in hist) / max(len(hist), 1)
        per = auto.get("target_num_ongoing_requests_per_replica", 2)
        cur = len(d["replicas"])
        want = max(auto.get("min_replicas", 1),
                   min(auto.get("max_replicas", 4),
                       int((avg + per - 1) // per) or 1))
        if want == cur:
            self._pending_scale.pop(name, None)
            return None
        direction = "up" if want > cur else "down"
        delay = float(auto.get("upscale_delay_s", 30.0) if direction == "up"
                      else auto.get("downscale_delay_s", 600.0))
        pend = self._pending_scale.get(name)
        if pend is None or pend[0] != direction:
            self._pending_scale[name] = (direction, now, want)
            pend = self._pending_scale[name]
        if now - pend[1] >= delay:
            self._pending_scale.pop(name, None)
            return want
        return None

    # ---- per-model autoscaling (multiplexed deployments) -------------------

    def _models_tick(self, name: str, d: dict):
        """One control-loop tick of the per-model scaler: poll each
        replica's model_stats, fold in the routers' per-model demand,
        and size every model's serving set toward
        load / target_load_per_model_replica (look-back averaged, with
        up/down delays). Scale ops run on a background thread — loading
        a model can take seconds and must not stall the control loop."""
        mcfg = d["config"].get("model_autoscaling_config")
        if not mcfg:
            return
        replicas = list(d["replicas"])
        if not replicas:
            return
        try:
            res = ray_tpu.get(
                [r.handle_request.remote("model_stats", (), {}, None)
                 for r in replicas], timeout=5)
        except Exception:
            return
        stats = [(r, st if isinstance(st, dict) else {})
                 for r, st in zip(replicas, res)]
        serving: Dict[str, list] = {}     # model -> replica indices
        local_load: Dict[str, float] = {}
        for i, (_, st) in enumerate(stats):
            for m in st.get("models", []):
                serving.setdefault(m, []).append(i)
            for m, q in (st.get("queues") or {}).items():
                local_load[m] = local_load.get(m, 0.0) + float(q)
        ext = self._ext_model_load(name)
        models = set(serving) | set(ext) | set(local_load)
        if not models:
            self._model_table[name] = {"ts": time.time(), "models": {}}
            return
        from ray_tpu.core.config import GLOBAL_CONFIG
        per = float(mcfg.get("target_load_per_model_replica",
                             GLOBAL_CONFIG.serve_model_target_load))
        look_back = float(mcfg.get("look_back_period_s", 10.0))
        mn = int(mcfg.get("min_replicas_per_model", 1))
        mx = int(mcfg.get("max_replicas_per_model", len(replicas)))
        now = time.time()
        table: Dict[str, dict] = {}
        for m in sorted(models):
            load = local_load.get(m, 0.0) + ext.get(m, 0.0)
            hist = self._mhist.setdefault((name, m), [])
            hist.append((now, load))
            while hist and hist[0][0] < now - look_back:
                hist.pop(0)
            avg = sum(v for _, v in hist) / max(len(hist), 1)
            cur = len(serving.get(m, []))
            # math.ceil, not the integer (a+b-1)//b idiom: `per` is a
            # float knob and fractional targets must still round UP
            want = math.ceil(avg / per) if per > 0 else mx
            want = max(mn, min(mx, want))
            table[m] = {"serving": cur, "want": want, "load": load,
                        "avg_load": avg}
            if want == cur:
                self._pending_mscale.pop((name, m), None)
                continue
            if (name, m) in self._model_ops:
                continue   # previous op for this model still running
            direction = "up" if want > cur else "down"
            delay = float(mcfg.get("upscale_delay_s", 0.0)
                          if direction == "up"
                          else mcfg.get("downscale_delay_s", 5.0))
            pend = self._pending_mscale.get((name, m))
            if pend is None or pend[0] != direction:
                self._pending_mscale[(name, m)] = (direction, now)
                pend = self._pending_mscale[(name, m)]
            if now - pend[1] >= delay:
                self._pending_mscale.pop((name, m), None)
                self._model_ops.add((name, m))
                threading.Thread(
                    target=self._apply_model_scale,
                    args=(name, m, want, stats, serving.get(m, [])),
                    daemon=True).start()
        self._model_table[name] = {"ts": now, "models": table}

    def _apply_model_scale(self, name: str, model: str, want: int,
                           stats: List[tuple], serving_idx: List[int]):
        """Background scale op for one model. Up: warm-load on the
        least-loaded replicas not yet serving it. Down: unpublish (the
        replica stops advertising, routers drain away), poll the
        per-model queue to 0 under serve_drain_timeout_s, then unload —
        PR 10's drain protocol applied at model granularity."""
        from ray_tpu.core.config import GLOBAL_CONFIG
        try:
            cur = len(serving_idx)
            if want > cur:
                # candidates: replicas not serving the model, coldest
                # (fewest queued requests across their models) first
                cand = [(sum((st.get("queues") or {}).values()),
                         len(st.get("resident", [])), i, r)
                        for i, (r, st) in enumerate(stats)
                        if i not in serving_idx and not st.get("draining")]
                cand.sort(key=lambda t: (t[0], t[1]))
                for _, _, _, r in cand[:want - cur]:
                    try:
                        ray_tpu.get(r.handle_request.remote(
                            "load_model", (model,), {}, None), timeout=120)
                    except Exception:
                        pass   # replica died/failed: next tick retries
                return
            # scale-down: retire from the highest index (arbitrary but
            # stable), keeping `want` replicas serving
            victims = [stats[i][0] for i in serving_idx[want:]]
            for r in victims:
                try:
                    ray_tpu.get(r.handle_request.remote(
                        "unpublish_model", (model,), {}, None), timeout=10)
                except Exception:
                    continue
            deadline = time.time() + GLOBAL_CONFIG.serve_drain_timeout_s
            pending = list(victims)
            while pending and time.time() < deadline \
                    and not self._stop.is_set():
                still = []
                for r in pending:
                    try:
                        q = ray_tpu.get(r.handle_request.remote(
                            "model_queue_len", (model,), {}, None),
                            timeout=5)
                        if int(q) > 0:
                            still.append(r)
                    except Exception:
                        pass   # dead: drained by definition
                pending = still
                if pending:
                    self._stop.wait(0.2)
            for r in victims:
                try:
                    ray_tpu.get(r.handle_request.remote(
                        "unload_model", (model,), {}, None), timeout=30)
                except Exception:
                    pass
        finally:
            self._model_ops.discard((name, model))

    def _control_loop(self):
        """Dead-replica replacement + windowed autoscaling."""
        while not self._stop.wait(1.0):
            for name in list(self.deployments):
                d = self.deployments.get(name)
                if d is None:
                    continue
                # replace dead replicas
                alive, alive_names = [], []
                for r, rn in zip(d["replicas"], d["replica_names"]):
                    try:
                        ray_tpu.get(r.queue_len.remote(), timeout=5)
                        alive.append(r)
                        alive_names.append(rn)
                    except Exception:
                        pass
                if len(alive) != len(d["replicas"]):
                    with self._lock:
                        d["replicas"] = alive
                        d["replica_names"] = alive_names
                    self._reconcile(name)
                    continue
                try:
                    self._models_tick(name, d)
                except Exception:
                    pass   # per-model scaler must never kill the loop
                if not d["config"].get("autoscaling_config"):
                    continue
                try:
                    qs = ray_tpu.get([r.queue_len.remote()
                                      for r in d["replicas"]], timeout=5)
                except Exception:
                    continue
                total = sum(qs) + self._ext_load_total(name)
                want = self._autoscale_decision(name, d, total)
                if want is not None and want != len(d["replicas"]):
                    with self._lock:
                        d["config"]["num_replicas"] = want
                    self._reconcile(name)
