"""ray_tpu.serve: model serving on actor replicas.

Reference: python/ray/serve/ — @serve.deployment + serve.run (api.py:242,414)
→ detached ServeController actor (controller.py:74) reconciling replica
actors (deployment_state.py:1097), client-side Router with
power-of-two-choices (router.py:262), @serve.batch dynamic batching
(batching.py:65), queue-depth autoscaling (autoscaling_policy.py).

TPU-first addition: ray_tpu.serve.llm — a continuous-batching LLM replica
(static-shape decode slots + bucketed prefill over the KV cache in HBM),
the design the reference lacks natively (SURVEY.md §7.9).

    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Model.bind())
    ref = handle.remote(21)
"""

from ray_tpu.serve.api import (Application, Deployment, deployment,
                               get_deployment_handle, run, shutdown, start,
                               status)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.disagg import DisaggRouter
from ray_tpu.serve.kv_transfer import (HandoffAdopter, HandoffExporter,
                                       PrefixDirectory)
from ray_tpu.serve.graph import DAGDriverImpl, InputNode, build_app
from ray_tpu.serve.grpc_proxy import (GrpcServeClient, shutdown_grpc,
                                      start_grpc)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.http_proxy import Request, Response
from ray_tpu.serve.llm_deployment import SimLLMServer, build_llm_app
from ray_tpu.serve.llm_router import LLMRouter
from ray_tpu.serve.multiplex import (ModelRegistry, get_multiplexed_model_id,
                                     get_request_tenant, multiplexed)

__all__ = [
    "deployment", "run", "shutdown", "start", "status",
    "get_deployment_handle", "batch", "Deployment", "Application",
    "DeploymentHandle", "Request", "Response", "multiplexed",
    "get_multiplexed_model_id", "get_request_tenant", "ModelRegistry",
    "build_app", "InputNode", "DAGDriverImpl",
    "start_grpc", "shutdown_grpc", "GrpcServeClient",
    "LLMRouter", "SimLLMServer", "build_llm_app",
    "DisaggRouter", "PrefixDirectory", "HandoffExporter", "HandoffAdopter",
]
