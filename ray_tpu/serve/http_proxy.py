"""HTTP ingress proxy.

Reference: python/ray/serve/_private/http_proxy.py:11 — per-node HTTPProxy
actors (uvicorn/starlette ASGI) that resolve a route table pushed from the
controller and forward requests to replicas via the router. Here the proxy
is an actor running a stdlib ThreadingHTTPServer (no ASGI dependency); each
handler thread forwards through a DeploymentHandle (P2C router) and maps
Python results to HTTP responses.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

import ray_tpu

PROXY_NAME = "_serve_http_proxy"
_NAMESPACE = "serve"


class Request:
    """What an ingress deployment receives for an HTTP call (the moral
    equivalent of the reference's starlette.requests.Request)."""

    def __init__(self, method: str, path: str, query: Dict[str, list],
                 headers: Dict[str, str], body: bytes,
                 route_prefix: str = "/"):
        self.method = method
        self.path = path
        self.query_params = {k: v[0] if len(v) == 1 else v
                             for k, v in query.items()}
        self.headers = headers
        self.body = body
        self.route_prefix = route_prefix

    def json(self) -> Any:
        return json.loads(self.body.decode() or "null")

    def text(self) -> str:
        return self.body.decode()

    def __repr__(self):
        return f"Request({self.method} {self.path})"


class Response:
    """Explicit response wrapper (status/headers control)."""

    def __init__(self, content: Any = "", status_code: int = 200,
                 media_type: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.content = content
        self.status_code = status_code
        self.media_type = media_type
        self.headers = headers or {}


def _encode_result(result: Any) -> tuple:
    """(status, content_type, payload_bytes)"""
    if isinstance(result, Response):
        status = result.status_code
        body = result.content
        ctype = result.media_type
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
            ctype = ctype or "application/json"
        elif isinstance(body, str):
            body = body.encode()
            ctype = ctype or "text/plain; charset=utf-8"
        elif not isinstance(body, (bytes, bytearray)):
            body = str(body).encode()
            ctype = ctype or "text/plain; charset=utf-8"
        return status, ctype, bytes(body), result.headers
    if isinstance(result, (dict, list)) or result is None:
        return 200, "application/json", json.dumps(result).encode(), {}
    if isinstance(result, (bytes, bytearray)):
        return 200, "application/octet-stream", bytes(result), {}
    if isinstance(result, str):
        return 200, "text/plain; charset=utf-8", result.encode(), {}
    return 200, "text/plain; charset=utf-8", str(result).encode(), {}


@ray_tpu.remote
class HTTPProxy:
    """One per node in the reference (http_state.py); here one per cluster,
    started by serve.start()."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 controller_name: str = "_serve_controller"):
        from ray_tpu.serve.handle import DeploymentHandle

        self._controller_name = controller_name
        self._routes: Dict[str, str] = {}   # route_prefix -> deployment
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes_lock = threading.Lock()
        self._refresh_routes()
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _dispatch(self):
                try:
                    out = proxy._handle(self)
                    if out[0] == "stream":
                        self._stream_out(out[1], out[2])
                        return
                    status, ctype, body, extra = out
                except Exception as e:  # noqa: BLE001 — proxy must not die
                    import traceback

                    body = json.dumps({"error": str(e),
                                       "traceback": traceback.format_exc()
                                       }).encode()
                    status, ctype, extra = 500, "application/json", {}
                self.send_response(status)
                self.send_header("Content-Type",
                                 ctype or "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _stream_out(self, ctype, chunks):
                """Chunked transfer encoding over the handler socket: each
                stream item flushes as its own chunk, so clients see
                tokens as they are generated. The first chunk is pulled
                BEFORE the headers commit, so an immediately-failing
                stream still gets a clean 500; later failures must not
                write a status line into the chunk framing — they emit an
                error chunk and terminate the stream instead."""
                it = iter(chunks)
                try:
                    first = next(it, None)
                except Exception:   # noqa: BLE001 — headers not sent yet
                    raise           # -> _dispatch's 500 path
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    try:
                        for data in itertools.chain(
                                [] if first is None else [first], it):
                            if not data:
                                continue
                            self.wfile.write(
                                f"{len(data):x}\r\n".encode() + data
                                + b"\r\n")
                            self.wfile.flush()
                    except Exception as e:  # noqa: BLE001 mid-stream error
                        err = json.dumps({"error": str(e)}).encode() + b"\n"
                        self.wfile.write(
                            f"{len(err):x}\r\n".encode() + err + b"\r\n")
                        self.close_connection = True   # stream cut short
                    self.wfile.write(b"0\r\n\r\n")
                except BrokenPipeError:
                    pass   # client went away; generator cleanup in chunks()

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _dispatch

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._refresher = threading.Thread(target=self._refresh_loop,
                                           daemon=True)
        self._refresher.start()

    # ---- routing table (ref: _private/long_poll.py push of route table) ----

    def _refresh_routes(self):
        try:
            controller = ray_tpu.get_actor(self._controller_name,
                                           namespace=_NAMESPACE)
            routes = ray_tpu.get(controller.get_routes.remote(), timeout=5)
        except Exception:
            return
        with self._routes_lock:
            self._routes = routes

    def _refresh_loop(self):
        """Long-poll push: one pending controller call returns the new
        route table the moment it changes (ref: long_poll.py:187); the
        except path degrades to a 1 s retry while the controller is
        down/restarting."""
        gen = 0
        while True:
            try:
                controller = ray_tpu.get_actor(self._controller_name,
                                               namespace=_NAMESPACE)
                res = ray_tpu.get(
                    controller.long_poll.remote("routes", gen, 10.0),
                    timeout=30)
                changed = res["gen"] != gen
                gen = res["gen"]
                if changed and res["value"] is not None:
                    with self._routes_lock:
                        self._routes = res["value"]
            except Exception:
                time.sleep(1.0)

    def _resolve(self, path: str) -> tuple:
        """Longest-prefix match over route table."""
        with self._routes_lock:
            routes = dict(self._routes)
        best = None
        for prefix, name in routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(
                    norm + "/") or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, name)
        return best

    # ---- request path (hot loop: parse → route → handle → encode) ----

    def _handle(self, h) -> tuple:
        parsed = urlparse(h.path)
        match = self._resolve(parsed.path)
        if match is None:
            # route table may be stale (deploy raced the refresh loop)
            self._refresh_routes()
            match = self._resolve(parsed.path)
        if match is None:
            return (404, "application/json",
                    json.dumps({"error": f"no route for {parsed.path}"
                                }).encode(), {})
        prefix, deployment = match
        length = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(length) if length else b""
        req = Request(h.command, parsed.path, parse_qs(parsed.query),
                      dict(h.headers.items()), body, prefix)
        handle = self._handles.get(deployment)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(deployment)
            self._handles[deployment] = handle
        if req.query_params.get("stream") == "1" or \
                "text/event-stream" in h.headers.get("Accept", ""):
            # Streaming contract: the deployment defines `stream_request`
            # (sync/async generator); items flush to the client as HTTP
            # chunks in yield order (ref: serve response streaming over
            # obj-ref generators). Clients accepting text/event-stream
            # get SSE framing (data: <json>\n\n per item).
            sse = "text/event-stream" in h.headers.get("Accept", "")
            gen = handle.options(stream=True).method(
                "stream_request").remote(req)
            # Pull the FIRST item here, before any status line commits:
            # a shed stream's typed first frame ({"status": 429, ...},
            # the LLMQueueFull contract) becomes a real 429 +
            # Retry-After instead of a 200 stream the client must parse.
            it = iter(gen)
            first = None
            try:
                ref = next(it, None)
                first = ray_tpu.get(ref) if ref is not None else None
            except StopIteration:
                pass
            if isinstance(first, dict) and first.get("status") == 429:
                retry = first.get("retry_after_s", 1.0)
                return (429, "application/json",
                        json.dumps(first).encode(),
                        {"Retry-After": f"{retry:g}"})
            ctype = ("text/event-stream" if sse
                     else "text/plain; charset=utf-8")
            return ("stream", ctype, self._iter_chunks(it, first, sse))
        # Retry-on-dead-replica (ref: router.py assign-and-retry): a
        # request that raced a replica death re-routes through the handle
        # (whose router gets the replacement set pushed) instead of
        # surfacing a 500. The owner runtime stamps the error with whether
        # the call frame ever reached the replica's worker: an UNSENT
        # request (dispatched=False) is safe to re-dispatch for ANY verb —
        # it provably never started, so no side effects can duplicate
        # (ref: router.py re-dispatches queued-but-unsent requests on
        # replica death regardless of method). Only idempotent methods
        # (GET/HEAD) may additionally retry after an IN-FLIGHT death,
        # where "died mid-write" cannot be ruled out.
        last_err = None
        idempotent = h.command in ("GET", "HEAD")
        for _ in range(3):
            ref = handle.remote(req)
            try:
                result = ray_tpu.get(ref, timeout=60)
                return _encode_result(result)
            except (ray_tpu.exceptions.ActorDiedError,
                    ray_tpu.exceptions.ActorUnavailableError) as e:
                last_err = e
                router = handle._get_router()
                # evict the EXACT dead replica locally — the controller's
                # next health probe (and pushed update) may be up to a
                # second away, and re-picking from a stale set would burn
                # every retry on the same corpse. Evict even when about to
                # surface the error, so later requests don't re-pick it.
                router.evict(getattr(e, "actor_id", None))
                if not router._replicas:
                    router._refresh(force=True)
                if not idempotent and getattr(e, "dispatched", True):
                    raise   # may have executed: never duplicate a POST
        raise last_err

    @staticmethod
    def _iter_chunks(gen, first=None, sse=False):
        def encode(item):
            if isinstance(item, (bytes, bytearray)):
                data = bytes(item)
            elif isinstance(item, str):
                data = item.encode()
            else:
                data = json.dumps(item).encode()
            if sse:
                return b"data: " + data + b"\n\n"
            if not isinstance(item, (bytes, bytearray, str)):
                data += b"\n"
            return data

        if first is not None:
            yield encode(first)
        for ref in gen:
            yield encode(ray_tpu.get(ref))

    def ready(self) -> int:
        return self.port

    def shutdown(self):
        self._server.shutdown()
        return True
