"""DeploymentHandle + Router: client-side replica scheduling.

Reference: python/ray/serve/handle.py:86 (RayServeHandle) and
_private/router.py:262 (PowerOfTwoChoicesReplicaScheduler). The router
keeps a local in-flight counter per replica and picks the less-loaded of
two random candidates. Replica-set changes are PUSHED from the controller
over a pending long-poll call (ref: _private/long_poll.py:69 LongPollClient
— one blocking RPC held open per channel instead of a 5 s timer), so a
deploy/scale/replica-death propagates to every router in one RPC round
trip rather than a poll interval.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class Router:
    """In-flight counts are keyed by replica ACTOR ID, not list index:
    the pushed replacement set reorders/reuses indices, so an index-keyed
    count stranded by a replica death (its done() never ran) would
    permanently bias the power-of-two picker away from whichever healthy
    replica later occupies that slot. Keyed by identity, a dead replica's
    count dies with it (evict pops the key) and survivors keep their real
    counts across set pushes."""

    def __init__(self, deployment_name: str, controller_name: str = "_serve_controller"):
        self.deployment_name = deployment_name
        self.controller_name = controller_name
        self._replicas: List[Any] = []
        self._inflight: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        self._gen = 0
        self._poller_started = False

    @staticmethod
    def _key(replica) -> str:
        return replica._actor_id.hex()

    def _set_replicas(self, replicas: List[Any]):
        """Adopt a new replica set (caller holds self._lock): keep live
        counts for replicas still in the set, drop counts of the gone
        (decrement-on-evict — their in-flight work died with them)."""
        keep = {self._key(r) for r in replicas}
        self._replicas = replicas
        self._inflight = {k: v for k, v in self._inflight.items()
                          if k in keep}

    def _ensure_poller(self):
        if self._poller_started:
            return
        self._poller_started = True
        threading.Thread(target=self._poll_loop, daemon=True).start()

    def _poll_loop(self):
        """Long-poll push loop: one pending controller call per router;
        returns immediately when the replica set changes (see
        ServeController.long_poll)."""
        key = f"replicas:{self.deployment_name}"
        while True:
            try:
                controller = ray_tpu.get_actor(self.controller_name,
                                               namespace="serve")
                res = ray_tpu.get(
                    controller.long_poll.remote(key, self._gen, 10.0),
                    timeout=30)
                changed = res["gen"] != self._gen
                self._gen = res["gen"]
                if changed and res["value"] is not None:
                    with self._lock:
                        self._set_replicas(res["value"])
                        self._last_refresh = time.time()
            except Exception:
                # controller down/restarting: back off, then re-resolve
                # the (possibly restarted) named actor and re-subscribe
                time.sleep(1.0)

    def _refresh(self, force: bool = False):
        now = time.time()
        if not force and self._replicas:
            return
        controller = ray_tpu.get_actor(self.controller_name, namespace="serve")
        replicas = ray_tpu.get(
            controller.get_replicas.remote(self.deployment_name))
        with self._lock:
            self._set_replicas(replicas)
            self._last_refresh = now

    def pick(self) -> tuple:
        self._ensure_poller()
        self._refresh()
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"no replicas for deployment {self.deployment_name!r}")
            if n == 1:
                i = 0
            else:
                a, b = random.sample(range(n), 2)
                ka = self._key(self._replicas[a])
                kb = self._key(self._replicas[b])
                i = (a if self._inflight.get(ka, 0)
                     <= self._inflight.get(kb, 0) else b)
            key = self._key(self._replicas[i])
            self._inflight[key] = self._inflight.get(key, 0) + 1
            return key, self._replicas[i]

    def done(self, key: str):
        with self._lock:
            if self._inflight.get(key, 0) > 0:
                self._inflight[key] -= 1

    def evict(self, actor_hex: Optional[str]):
        """Drop a dead replica from the local set IMMEDIATELY (ref:
        router.py on-ActorDiedError eviction): a retry must not wait for
        the controller's next health probe to stop targeting it. The
        pushed replacement set supersedes this on arrival; survivors keep
        their in-flight counts, the dead replica's count is discarded."""
        if not actor_hex:
            return
        with self._lock:
            keep = [r for r in self._replicas
                    if r._actor_id.hex() != actor_hex]
            if len(keep) != len(self._replicas):
                self._set_replicas(keep)


class DeploymentHandle:
    """Serializable; rebuilds its router lazily in the holding process."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._router: Optional[Router] = None
        self._context: dict = {}
        self._stream = False

    def _get_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.deployment_name)
        return self._router

    def options(self, *, multiplexed_model_id: Optional[str] = None,
                tenant: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        """Per-call options (ref: handle.options(multiplexed_model_id=...,
        stream=True)). stream=True makes .remote() return an
        ObjectRefGenerator of the handler's yielded items. tenant tags
        the call for the router's weighted-fair admission and rides the
        same context channel as the model id."""
        h = DeploymentHandle(self.deployment_name)
        h._router = self._get_router()     # share router state
        h._context = dict(self._context)
        h._stream = self._stream if stream is None else stream
        if multiplexed_model_id is not None:
            h._context["multiplexed_model_id"] = multiplexed_model_id
        if tenant is not None:
            h._context["tenant"] = tenant
        return h

    def remote(self, *args, **kwargs):
        return self._call("__call__", args, kwargs)

    def method(self, name: str):
        handle = self

        class _M:
            def remote(self, *args, **kwargs):
                return handle._call(name, args, kwargs)

        return _M()

    def _call(self, method: str, args, kwargs):
        router = self._get_router()
        entry = ("handle_request_streaming" if getattr(self, "_stream", False)
                 else "handle_request")
        for attempt in range(3):
            key, replica = router.pick()
            try:
                ref = getattr(replica, entry).remote(
                    method, args, kwargs, self._context or None)
                router.done(key)
                return ref
            except (ray_tpu.exceptions.ActorDiedError,
                    ray_tpu.exceptions.ActorUnavailableError) as e:
                router.done(key)
                router.evict(getattr(e, "actor_id", None))
                if not router._replicas:
                    router._refresh(force=True)
        raise RuntimeError(
            f"could not reach a replica of {self.deployment_name}")

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"
