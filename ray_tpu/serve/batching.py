"""@serve.batch: dynamic request batching inside a replica.

Reference: python/ray/serve/batching.py:65 (_BatchQueue) — async requests
accumulate until max_batch_size or batch_wait_timeout_s, then the wrapped
function is called once with the list; results fan back out per-caller.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.queue: List = []          # (item, future)
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, instance, item) -> Any:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.queue.append((item, fut))
        if len(self.queue) >= self.max_batch_size:
            await self._flush(instance)
        elif self._flusher is None or self._flusher.done():
            self._flusher = loop.create_task(self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self.timeout_s)
        await self._flush(instance)

    async def _flush(self, instance):
        batch, self.queue = self.queue, []
        if not batch:
            return
        items = [b[0] for b in batch]
        try:
            if instance is not None:
                results = self.fn(instance, items)
            else:
                results = self.fn(items)
            if asyncio.iscoroutine(results):
                results = await results
            if len(results) != len(items):
                raise ValueError(
                    f"batched fn returned {len(results)} results for "
                    f"{len(items)} inputs")
            for (_, fut), r in zip(batch, results):
                if not fut.done():
                    fut.set_result(r)
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate an async method taking a LIST of requests."""

    def deco(fn):
        q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:          # bound method (self, item)
                return await q.submit(args[0], args[1])
            return await q.submit(None, args[0])

        wrapper._batch_queue = q
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
