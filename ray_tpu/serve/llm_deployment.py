"""LLM serving application builder + deterministic sim replica.

build_llm_app composes the two-tier serving graph the router needs
(ref: serve deployment-graph composition, api.py bind/_handleize):

    LLMRouter (ingress, 1 replica)  ->  LLMServer x N (paged KV engines)

serve.run deploys children first, so the router's injected
DeploymentHandle resolves live replicas immediately.

SimLLMServer is a deterministic LLMServer stand-in for router tests and
the serve_router bench: it honors the same streaming contract
(stream_request frames, LLMQueueFull-as-429 first frame), the same
stats() fields the router's pressure score reads, and a prefix cache
with the same register/match semantics — but its "generation" is
asyncio.sleep-based, so routing properties (affinity hit rate, shed
behavior, failover token continuity, replica scaling) are measured as
real wall-clock effects without a jax engine. Token i of a submission
whose prompt has L tokens is L + i: after a mid-stream failover
resubmits prompt+generated, the continuation is exactly the next
integer — token continuity asserts are exact, not statistical.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve import api as serve_api
from ray_tpu.serve.llm_router import LLMRouter

_PAGE = 16   # sim prefix-cache granularity (tokens per "page")


class SimLLMServer:
    """Deterministic fake engine with real queueing/caching dynamics."""

    def __init__(self, *, max_slots: int = 8,
                 max_queue_depth: Optional[int] = 64,
                 prefill_s_per_token: float = 0.0002,
                 decode_s_per_token: float = 0.002,
                 tokens_per_frame: int = 4,
                 prefix_caching: bool = True,
                 prefix_cache_pages: int = 64):
        self.max_slots = max_slots
        self.max_queue_depth = max_queue_depth
        self.prefill_s_per_token = prefill_s_per_token
        self.decode_s_per_token = decode_s_per_token
        self.tokens_per_frame = max(int(tokens_per_frame), 1)
        self.prefix_caching = prefix_caching
        self.prefix_cache_pages = prefix_cache_pages
        # LRU by insertion/touch order, like PagePool's reclaim of
        # refcount-0 cached pages: a replica whose routed working set
        # exceeds capacity THRASHES — the effect prefix affinity exists
        # to avoid (it partitions prefix groups across replicas so each
        # replica's set fits).
        from collections import OrderedDict

        self._cached_pages: "OrderedDict[tuple, None]" = OrderedDict()
        self._slots = asyncio.Semaphore(max_slots)
        self._pending = 0
        self._active = 0
        self._draining = False
        self._lock = threading.Lock()
        self.metrics: Dict[str, Any] = {
            "requests": 0, "tokens_generated": 0, "rejected": 0,
            "prefix_hits": 0, "prefix_hit_tokens": 0,
            "admit_s": 0.0, "decode_block_s": 0.0,
            "ttft_sum": 0.0, "ttft_count": 0}

    # -- prefix cache sim: leading full pages by content hash ---------------

    def _page_hashes(self, prompt: List[int]) -> List[tuple]:
        out, acc = [], []
        for i in range(0, len(prompt) - len(prompt) % _PAGE, _PAGE):
            acc.extend(prompt[i:i + _PAGE])
            out.append(tuple(acc))
        return out

    def _match_and_register(self, prompt: List[int]) -> int:
        if not self.prefix_caching:
            return 0
        hashes = self._page_hashes(prompt)
        matched = 0
        for h in hashes:
            if h in self._cached_pages:
                matched += _PAGE
            else:
                break
        for h in hashes:   # touch + register (LRU order)
            self._cached_pages[h] = None
            self._cached_pages.move_to_end(h)
        while len(self._cached_pages) > self.prefix_cache_pages:
            self._cached_pages.popitem(last=False)
        if matched:
            self.metrics["prefix_hits"] += 1
            self.metrics["prefix_hit_tokens"] += matched
        return matched

    # -- serving contract ----------------------------------------------------

    async def stream_request(self, request) -> Any:
        body = request if isinstance(request, dict) else request.json()
        prompt = list(body["prompt"])
        max_new = int(body.get("max_new_tokens", 32))
        with self._lock:
            backlog = self._pending + self._active
            if self._draining or (self.max_queue_depth is not None
                                  and backlog >= self.max_queue_depth):
                self.metrics["rejected"] += 1
                shed = True
            else:
                self.metrics["requests"] += 1
                self._pending += 1
                shed = False
        if shed:
            yield {"error": "sim queue full" if not self._draining
                   else "replica draining", "status": 429, "done": True}
            return
        t_sub = time.time()
        async with self._slots:
            with self._lock:
                self._pending -= 1
                self._active += 1
                matched = self._match_and_register(prompt)
            try:
                t0 = time.time()
                # prefill cost scales with the UNCACHED prompt tail —
                # this is the wall-clock effect prefix affinity buys
                await asyncio.sleep(
                    self.prefill_s_per_token * (len(prompt) - matched))
                dt = time.time() - t0
                with self._lock:
                    self.metrics["admit_s"] += dt
                L = len(prompt)
                ttft = None
                i = 0
                while i < max_new:
                    n = min(self.tokens_per_frame, max_new - i)
                    t1 = time.time()
                    await asyncio.sleep(self.decode_s_per_token * n)
                    with self._lock:
                        self.metrics["decode_block_s"] += time.time() - t1
                        self.metrics["tokens_generated"] += n
                    if ttft is None:
                        ttft = time.time() - t_sub
                        with self._lock:
                            self.metrics["ttft_sum"] += ttft
                            self.metrics["ttft_count"] += 1
                    yield {"tokens": [L + j for j in range(i, i + n)]}
                    i += n
                yield {"done": True, "n_tokens": max_new, "ttft_s": ttft}
            finally:
                with self._lock:
                    self._active -= 1

    async def __call__(self, request) -> Dict[str, Any]:
        tokens: List[int] = []
        final: Dict[str, Any] = {}
        async for frame in self.stream_request(request):
            if frame.get("status") == 429:
                from ray_tpu.serve.http_proxy import Response

                return Response({"error": frame.get("error")},
                                status_code=429,
                                headers={"Retry-After": "1"})
            if frame.get("done"):
                final = frame
            tokens.extend(frame.get("tokens", []))
        return {"tokens": tokens, "ttft_s": final.get("ttft_s")}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            m = dict(self.metrics)
            m["pending"] = self._pending
            m["active_slots"] = self._active
            m["max_slots"] = self.max_slots
            m["draining"] = self._draining
        if m["ttft_count"]:
            m["mean_ttft_s"] = m["ttft_sum"] / m["ttft_count"]
        return m

    def queue_len(self) -> int:
        with self._lock:
            return self._pending + self._active

    def drain(self) -> None:
        self._draining = True


def build_llm_app(*, name: str = "llm_server",
                  num_replicas: int = 2,
                  router_policy: str = "affinity",
                  autoscaling_config: Optional[dict] = None,
                  use_sim: bool = False,
                  router_kwargs: Optional[dict] = None,
                  **llm_kwargs) -> Any:
    """Build the router-fronted serving application. llm_kwargs go to
    LLMServer (preset, max_slots, kv_layout, ...) — or to SimLLMServer
    when use_sim=True (tests/bench). Returns the Application; deploy
    with serve.run(app, route_prefix=...)."""
    if use_sim:
        server_cls = SimLLMServer
    else:
        from ray_tpu.serve.llm import LLMServer

        server_cls = LLMServer
    llm = serve_api.deployment(
        server_cls, name=name, num_replicas=num_replicas,
        autoscaling_config=autoscaling_config).bind(**llm_kwargs)
    router = serve_api.deployment(
        LLMRouter, name=f"{name}_router", num_replicas=1).bind(
        llm, policy=router_policy, **(router_kwargs or {}))
    return router
