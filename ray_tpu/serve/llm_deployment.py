"""LLM serving application builder + deterministic sim replica.

build_llm_app composes the two-tier serving graph the router needs
(ref: serve deployment-graph composition, api.py bind/_handleize):

    LLMRouter (ingress, 1 replica)  ->  LLMServer x N (paged KV engines)

serve.run deploys children first, so the router's injected
DeploymentHandle resolves live replicas immediately.

SimLLMServer is a deterministic LLMServer stand-in for router tests and
the serve_router bench: it honors the same streaming contract
(stream_request frames, LLMQueueFull-as-429 first frame), the same
stats() fields the router's pressure score reads, and a prefix cache
with the same register/match semantics — but its "generation" is
asyncio.sleep-based, so routing properties (affinity hit rate, shed
behavior, failover token continuity, replica scaling) are measured as
real wall-clock effects without a jax engine. Token i of a submission
whose prompt has L tokens is L + i: after a mid-stream failover
resubmits prompt+generated, the continuation is exactly the next
integer — token continuity asserts are exact, not statistical.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve import api as serve_api
from ray_tpu.serve.llm_router import LLMRouter

_PAGE = 16   # sim prefix-cache granularity (tokens per "page")


class SimLLMServer:
    """Deterministic fake engine with real queueing/caching dynamics."""

    def __init__(self, *, max_slots: int = 8,
                 max_queue_depth: Optional[int] = 64,
                 prefill_s_per_token: float = 0.0002,
                 decode_s_per_token: float = 0.002,
                 tokens_per_frame: int = 4,
                 prefix_caching: bool = True,
                 prefix_cache_pages: int = 64,
                 mode: str = "monolithic",
                 page_tokens: int = _PAGE,
                 group_pages: int = 4,
                 retained_groups: int = 512,
                 use_directory: bool = True,
                 colocation_interference: float = 0.0,
                 multiplexed: bool = False,
                 max_models: Optional[int] = None,
                 model_load_s: float = 0.05,
                 model_load_fail_ids: Optional[List[str]] = None):
        if mode not in ("monolithic", "prefill", "decode"):
            raise ValueError(f"unknown SimLLMServer mode {mode!r}")
        self.mode = mode
        self.page_tokens = int(page_tokens)
        self.group_pages = int(group_pages)
        self.retained_groups = int(retained_groups)
        self.use_directory = use_directory
        self._exporter = None   # lazy: needs the in-actor runtime
        self._adopter = None
        self.max_slots = max_slots
        self.max_queue_depth = max_queue_depth
        self.prefill_s_per_token = prefill_s_per_token
        self.decode_s_per_token = decode_s_per_token
        self.tokens_per_frame = max(int(tokens_per_frame), 1)
        self.prefix_caching = prefix_caching
        self.prefix_cache_pages = prefix_cache_pages
        # co-location contention model (ref: DistServe §2): a prefill
        # sharing the engine inflates every co-scheduled decode step by
        # this factor per co-running prefill. A replica that runs only
        # one phase (mode="prefill"/"decode") never pays it — the effect
        # disaggregation removes.
        self.colocation_interference = float(colocation_interference)
        self._prefill_active = 0
        # LRU by insertion/touch order, like PagePool's reclaim of
        # refcount-0 cached pages: a replica whose routed working set
        # exceeds capacity THRASHES — the effect prefix affinity exists
        # to avoid (it partitions prefix groups across replicas so each
        # replica's set fits).
        from collections import OrderedDict

        self._cached_pages: "OrderedDict[tuple, None]" = OrderedDict()
        self._slots = asyncio.Semaphore(max_slots)
        self._pending = 0
        self._active = 0
        self._draining = False
        self._lock = threading.Lock()
        # --- model multiplexing (mirrors LLMServer's contract) --------------
        # A "loaded model" here is a token dict; loading costs
        # model_load_s of wall clock — the effect model-affinity routing
        # exists to avoid (a request landing on a cold replica pays it).
        self.multiplexed = multiplexed
        self.model_load_s = float(model_load_s)
        # fault injection for tests: loading any of these ids raises,
        # exercising the router's load-failure route-around
        self.model_load_fail_ids = set(model_load_fail_ids or ())
        from ray_tpu.core.config import GLOBAL_CONFIG as _gc
        from ray_tpu.serve.multiplex import _ModelCache
        self._models = _ModelCache(
            type(self)._load_model,
            max_models if max_models is not None
            else _gc.serve_max_models_per_replica,
            unloader=type(self)._unload_model)
        self._unpublished: set = set()
        self._model_backlog: Dict[str, int] = {}
        self.metrics: Dict[str, Any] = {
            "requests": 0, "tokens_generated": 0, "rejected": 0,
            "prefix_hits": 0, "prefix_hit_tokens": 0,
            "admit_s": 0.0, "decode_block_s": 0.0,
            "ttft_sum": 0.0, "ttft_count": 0,
            # disagg counters (stay 0 in monolithic mode)
            "prefills": 0, "prefill_tokens": 0,
            "global_prefix_hits": 0, "global_prefix_hit_tokens": 0,
            "decodes": 0, "handoffs_lost": 0,
            "interference_stall_s": 0.0,
            # multiplex counters + the per-request context observations
            # the compiled-vs-legacy propagation test asserts on
            "model_loads": 0, "model_evictions": 0,
            "ctx_model_ids": [], "ctx_tenants": []}

    # -- model multiplexing --------------------------------------------------

    async def _load_model(self, model_id: str) -> Dict[str, Any]:
        await asyncio.sleep(self.model_load_s)
        if model_id in self.model_load_fail_ids:
            raise RuntimeError(f"injected load failure for {model_id!r}")
        with self._lock:
            self.metrics["model_loads"] += 1
        return {"model_id": model_id}

    def _unload_model(self, model_id: str, obj) -> None:
        with self._lock:
            self.metrics["model_evictions"] += 1

    async def load_model(self, model_id: str) -> List[str]:
        self._unpublished.discard(model_id)
        await self._models.get(self, model_id)
        return self.loaded_models()

    def unpublish_model(self, model_id: str) -> bool:
        if model_id in self._models.cache:
            self._unpublished.add(model_id)
            return True
        return False

    async def unload_model(self, model_id: str) -> bool:
        self._unpublished.discard(model_id)
        return await self._models.unload(self, model_id)

    def loaded_models(self) -> List[str]:
        return [m for m in self._models.models()
                if m not in self._unpublished]

    def model_queue_len(self, model_id: str) -> int:
        with self._lock:
            return self._model_backlog.get(model_id, 0)

    def model_stats(self) -> Dict[str, Any]:
        with self._lock:
            queues = dict(self._model_backlog)
        return {
            "models": self.loaded_models(),
            "resident": self._models.models(),
            "queues": queues,
            "loads": self.metrics["model_loads"],
            "evictions": self.metrics["model_evictions"],
            "retiring": 0,
            "draining": self._draining,
        }

    # -- disagg plumbing (mode="prefill" / "decode") -------------------------

    def _ensure_transfer(self):
        """Lazily build the exporter/adopter pair: both need the
        in-actor runtime (zero-copy put/get + gcs_call), which exists
        once the replica runs but not necessarily at construction."""
        from ray_tpu.serve.kv_transfer import (HandoffAdopter,
                                               HandoffExporter,
                                               PrefixDirectory)
        if self._adopter is None:
            self._adopter = HandoffAdopter()
        if self._exporter is None and self.mode == "prefill":
            import uuid
            directory = PrefixDirectory() if self.use_directory else None
            self._exporter = HandoffExporter(
                owner=f"sim-{uuid.uuid4().hex[:12]}",
                page_tokens=self.page_tokens,
                group_pages=self.group_pages,
                retained_groups=self.retained_groups,
                directory=directory)

    def _global_adopt(self, prompt: List[int]) -> int:
        """Resolve the longest directory-warm leading run of page
        groups; groups owned elsewhere are fetched once (zero-copy get)
        and seeded into our exporter so OUR envelopes re-reference the
        original store objects instead of re-putting them. Returns warm
        tokens (any owner)."""
        from ray_tpu.serve.kv_transfer import group_boundary_hashes
        ex = self._exporter
        if ex is None or ex.directory is None:
            return 0
        gb = group_boundary_hashes(prompt, self.page_tokens,
                                   self.group_pages)
        hits = ex.directory.lookup(gb)
        warm, foreign = 0, []
        for h, e in zip(gb, hits):
            if e is None:
                break
            warm += 1
            if e["owner"] != ex.owner and not ex.has(h):
                foreign.append((h, e))
        if foreign:
            self._adopter.adopt({"groups": [
                {"hash": h, "ref": e["ref"], "nbytes": e["nbytes"]}
                for h, e in foreign]})
            ex.seed([(h, e["ref"], e["nbytes"]) for h, e in foreign])
        return warm * ex.group_tokens

    # -- prefix cache sim: leading full pages by content hash ---------------

    def _page_hashes(self, prompt: List[int]) -> List[tuple]:
        out, acc = [], []
        for i in range(0, len(prompt) - len(prompt) % _PAGE, _PAGE):
            acc.extend(prompt[i:i + _PAGE])
            out.append(tuple(acc))
        return out

    def _match_and_register(self, prompt: List[int]) -> int:
        if not self.prefix_caching:
            return 0
        hashes = self._page_hashes(prompt)
        matched = 0
        for h in hashes:
            if h in self._cached_pages:
                matched += _PAGE
            else:
                break
        for h in hashes:   # touch + register (LRU order)
            self._cached_pages[h] = None
            self._cached_pages.move_to_end(h)
        while len(self._cached_pages) > self.prefix_cache_pages:
            self._cached_pages.popitem(last=False)
        if matched:
            self.metrics["prefix_hits"] += 1
            self.metrics["prefix_hit_tokens"] += matched
        return matched

    # -- serving contract ----------------------------------------------------

    async def stream_request(self, request) -> Any:
        from ray_tpu.serve.multiplex import (get_multiplexed_model_id,
                                             get_request_tenant)
        body = request if isinstance(request, dict) else request.json()
        prompt = list(body["prompt"])
        max_new = int(body.get("max_new_tokens", 32))
        model = str(body.get("model") or get_multiplexed_model_id() or "")
        with self._lock:
            # record the context this call actually observed (the
            # compiled-vs-legacy propagation test reads these; bounded)
            for k, v in (("ctx_model_ids", get_multiplexed_model_id()),
                         ("ctx_tenants", get_request_tenant())):
                lst = self.metrics[k]
                lst.append(v)
                if len(lst) > 512:
                    del lst[:-256]
            backlog = self._pending + self._active
            if self._draining or (self.max_queue_depth is not None
                                  and backlog >= self.max_queue_depth):
                self.metrics["rejected"] += 1
                shed = True
            else:
                self.metrics["requests"] += 1
                self._pending += 1
                if model:
                    self._model_backlog[model] = \
                        self._model_backlog.get(model, 0) + 1
                shed = False
        if shed or (model and model in self._unpublished):
            if not shed:   # admitted above, roll back before shedding
                with self._lock:
                    self._pending -= 1
                    self._model_backlog[model] -= 1
                    self.metrics["requests"] -= 1
                    self.metrics["rejected"] += 1
            yield {"error": (f"model {model!r} draining on this replica"
                             if not shed else
                             "sim queue full" if not self._draining
                             else "replica draining"),
                   "status": 429, "done": True}
            return
        if model and self.multiplexed:
            try:
                # cold replicas pay the load here — the wall-clock cost
                # model-affinity routing avoids on warm replicas
                await self._models.get(self, model)
            except Exception as e:
                with self._lock:
                    self._pending -= 1
                    self._model_backlog[model] -= 1
                yield {"error": f"model load failed: {e}", "status": 503,
                       "done": True}
                return
        t_sub = time.time()
        async with self._slots:
            with self._lock:
                self._pending -= 1
                self._active += 1
                matched = self._match_and_register(prompt)
            try:
                t0 = time.time()
                # prefill cost scales with the UNCACHED prompt tail —
                # this is the wall-clock effect prefix affinity buys
                with self._lock:
                    self._prefill_active += 1
                try:
                    await asyncio.sleep(
                        self.prefill_s_per_token * (len(prompt) - matched))
                finally:
                    with self._lock:
                        self._prefill_active -= 1
                dt = time.time() - t0
                with self._lock:
                    self.metrics["admit_s"] += dt
                L = len(prompt)
                ttft = None
                i = 0
                while i < max_new:
                    n = min(self.tokens_per_frame, max_new - i)
                    t1 = time.time()
                    base = self.decode_s_per_token * n
                    with self._lock:
                        stall = base * self.colocation_interference \
                            * self._prefill_active
                        self.metrics["interference_stall_s"] += stall
                    await asyncio.sleep(base + stall)
                    with self._lock:
                        self.metrics["decode_block_s"] += time.time() - t1
                        self.metrics["tokens_generated"] += n
                    if ttft is None:
                        ttft = time.time() - t_sub
                        with self._lock:
                            self.metrics["ttft_sum"] += ttft
                            self.metrics["ttft_count"] += 1
                    yield {"tokens": [L + j for j in range(i, i + n)]}
                    i += n
                yield {"done": True, "n_tokens": max_new, "ttft_s": ttft}
            finally:
                with self._lock:
                    self._active -= 1
                    if model:
                        self._model_backlog[model] = max(
                            0, self._model_backlog.get(model, 0) - 1)

    async def prefill_request(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """mode="prefill": run (only) the prefill for `body["prompt"]`,
        export the filled page groups through the zero-copy store, and
        return the handoff envelope. Deterministic: prefill wall-clock
        scales with the tokens NOT covered by the replica-local page
        cache or the global prefix directory — a directory hit on a
        second replica skips the shared prefix entirely."""
        assert self.mode == "prefill", self.mode
        import numpy as np
        self._ensure_transfer()
        prompt = list(body["prompt"])
        with self._lock:
            backlog = self._pending + self._active
            if self._draining or (self.max_queue_depth is not None
                                  and backlog >= self.max_queue_depth):
                self.metrics["rejected"] += 1
                return {"error": "sim queue full" if not self._draining
                        else "replica draining", "status": 429}
            self.metrics["requests"] += 1
            self._pending += 1
        async with self._slots:
            with self._lock:
                self._pending -= 1
                self._active += 1
            try:
                t0 = time.time()
                matched = self._match_and_register(prompt)
                # directory lookup + store put are blocking runtime
                # calls — banned on the event-loop thread (raylint
                # blocking-in-async), so hop to an executor thread
                warm = await asyncio.to_thread(self._global_adopt, prompt)
                skip = max(matched, warm)
                if warm > matched:
                    with self._lock:
                        self.metrics["global_prefix_hits"] += 1
                        self.metrics["global_prefix_hit_tokens"] += \
                            warm - matched
                await asyncio.sleep(
                    self.prefill_s_per_token * (len(prompt) - skip))
                envelope = await asyncio.to_thread(
                    self._exporter.export,
                    prompt,
                    lambda s, e: np.asarray(prompt[s:e], np.int32),
                    lambda a: int(a.nbytes))
                dt = time.time() - t0
                with self._lock:
                    self.metrics["admit_s"] += dt
                    self.metrics["prefills"] += 1
                    self.metrics["prefill_tokens"] += len(prompt) - skip
                return {"envelope": envelope, "matched_tokens": skip,
                        "prefill_s": dt}
            finally:
                with self._lock:
                    self._active -= 1

    def ack_handoff(self, handoff_id: str) -> bool:
        """Router ack: the decode replica adopted (or the attempt was
        abandoned) — release this handoff's pins."""
        if self._exporter is None:
            return False
        return self._exporter.ack(handoff_id)

    async def adopt_decode(self, envelope: Dict[str, Any], body) -> Any:
        """mode="decode": map the envelope's page groups in from the
        store (no re-serialize), then stream decode frames with the same
        token-continuity contract as stream_request — token i of a
        prompt of length L is L + i, so failover asserts stay exact."""
        assert self.mode == "decode", self.mode
        self._ensure_transfer()
        body = body if isinstance(body, dict) else body.json()
        max_new = int(body.get("max_new_tokens", 32))
        with self._lock:
            backlog = self._pending + self._active
            if self._draining or (self.max_queue_depth is not None
                                  and backlog >= self.max_queue_depth):
                self.metrics["rejected"] += 1
                shed = True
            else:
                self.metrics["requests"] += 1
                self._pending += 1
                shed = False
        if shed:
            yield {"error": "sim queue full" if not self._draining
                   else "replica draining", "status": 429, "done": True}
            return
        t_sub = time.time()
        async with self._slots:
            with self._lock:
                self._pending -= 1
                self._active += 1
            try:
                try:
                    # blocking zero-copy gets: executor thread, not loop
                    await asyncio.to_thread(self._adopter.adopt, envelope)
                except Exception:
                    # the exporter (or its store) died before we mapped
                    # the pages in: tell the router to re-prefill
                    with self._lock:
                        self.metrics["handoffs_lost"] += 1
                    yield {"handoff_lost": True, "done": True}
                    return
                L = int(envelope.get("prompt_len", 0))
                ttft = None
                i = 0
                while i < max_new:
                    n = min(self.tokens_per_frame, max_new - i)
                    t1 = time.time()
                    await asyncio.sleep(self.decode_s_per_token * n)
                    with self._lock:
                        self.metrics["decode_block_s"] += time.time() - t1
                        self.metrics["tokens_generated"] += n
                    if ttft is None:
                        ttft = time.time() - t_sub
                        with self._lock:
                            self.metrics["ttft_sum"] += ttft
                            self.metrics["ttft_count"] += 1
                    yield {"tokens": [L + j for j in range(i, i + n)]}
                    i += n
                with self._lock:
                    self.metrics["decodes"] += 1
                yield {"done": True, "n_tokens": max_new, "ttft_s": ttft,
                       "handoff_id": envelope.get("handoff_id")}
            finally:
                with self._lock:
                    self._active -= 1

    async def __call__(self, request) -> Dict[str, Any]:
        tokens: List[int] = []
        final: Dict[str, Any] = {}
        async for frame in self.stream_request(request):
            if frame.get("status") == 429:
                from ray_tpu.serve.http_proxy import Response

                return Response({"error": frame.get("error")},
                                status_code=429,
                                headers={"Retry-After": "1"})
            if frame.get("done"):
                final = frame
            tokens.extend(frame.get("tokens", []))
        return {"tokens": tokens, "ttft_s": final.get("ttft_s")}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            m = dict(self.metrics)
            m["pending"] = self._pending
            m["active_slots"] = self._active
            m["max_slots"] = self.max_slots
            m["draining"] = self._draining
            m["mode"] = self.mode
            if self.multiplexed:
                m["model_queue"] = dict(self._model_backlog)
        if self.multiplexed:
            m["models"] = self.loaded_models()
        if m["ttft_count"]:
            m["mean_ttft_s"] = m["ttft_sum"] / m["ttft_count"]
        if self._exporter is not None:
            m.update({f"handoff_{k}": v
                      for k, v in self._exporter.stats().items()})
        if self._adopter is not None:
            m.update({f"adopt_{k}": v
                      for k, v in self._adopter.stats().items()})
        return m

    def queue_len(self) -> int:
        with self._lock:
            return self._pending + self._active

    def drain(self) -> None:
        self._draining = True
        if self._exporter is not None:
            # unpin retained + in-flight page groups and withdraw our
            # directory entries before the controller kills us
            self._exporter.close()


def build_llm_app(*, name: str = "llm_server",
                  num_replicas: int = 2,
                  router_policy: str = "affinity",
                  autoscaling_config: Optional[dict] = None,
                  model_autoscaling_config: Optional[dict] = None,
                  tenant_weights: Optional[dict] = None,
                  use_sim: bool = False,
                  router_kwargs: Optional[dict] = None,
                  disaggregated: bool = False,
                  prefill_replicas: Optional[int] = None,
                  decode_replicas: Optional[int] = None,
                  prefill_autoscaling_config: Optional[dict] = None,
                  decode_autoscaling_config: Optional[dict] = None,
                  **llm_kwargs) -> Any:
    """Build the router-fronted serving application. llm_kwargs go to
    LLMServer (preset, max_slots, kv_layout, ...) — or to SimLLMServer
    when use_sim=True (tests/bench). Returns the Application; deploy
    with serve.run(app, route_prefix=...).

    disaggregated=True builds the two-pool topology instead
    (serve/disagg.py): `{name}_prefill` x prefill_replicas and
    `{name}_decode` x decode_replicas behind a DisaggRouter ingress.
    Prefill replicas fill paged-KV pages and export them through the
    zero-copy store; decode replicas adopt and stream. Each pool
    autoscales independently (the router report_loads per pool)."""
    if use_sim:
        server_cls = SimLLMServer
    else:
        from ray_tpu.serve.llm import LLMServer

        server_cls = LLMServer
    if disaggregated:
        from ray_tpu.serve.disagg import DisaggRouter

        n_pf = prefill_replicas if prefill_replicas is not None \
            else max(1, num_replicas // 2)
        n_dec = decode_replicas if decode_replicas is not None \
            else max(1, num_replicas - n_pf)
        prefill = serve_api.deployment(
            server_cls, name=f"{name}_prefill", num_replicas=n_pf,
            autoscaling_config=prefill_autoscaling_config).bind(
            mode="prefill", **llm_kwargs)
        decode = serve_api.deployment(
            server_cls, name=f"{name}_decode", num_replicas=n_dec,
            autoscaling_config=decode_autoscaling_config).bind(
            mode="decode", **llm_kwargs)
        router = serve_api.deployment(
            DisaggRouter, name=f"{name}_router", num_replicas=1).bind(
            decode, prefill_app=prefill, policy=router_policy,
            **(router_kwargs or {}))
        return router
    llm = serve_api.deployment(
        server_cls, name=name, num_replicas=num_replicas,
        autoscaling_config=autoscaling_config,
        model_autoscaling_config=model_autoscaling_config).bind(
        **llm_kwargs)
    rkw = dict(router_kwargs or {})
    if tenant_weights is not None:
        rkw.setdefault("tenant_weights", tenant_weights)
    router = serve_api.deployment(
        LLMRouter, name=f"{name}_router", num_replicas=1).bind(
        llm, policy=router_policy, **rkw)
    return router
