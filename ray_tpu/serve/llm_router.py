"""LLMRouter: prefix-cache-aware routing across LLMServer replicas.

Multi-replica LLM serving needs a router that is smarter than the
generic power-of-two handle: paged-KV prefix caching (serve/paged_kv.py
PagePool + llm.py automatic prefix caching) makes replica choice
STATEFUL — a request whose prompt shares a prefix with earlier traffic
is dramatically cheaper on the replica that already holds those KV
pages (TTFT skips the prefix's prefill compute AND its page memory).
Ref: vLLM's prefix-aware routing in production routers (e.g. the
llm-d / vllm-router session-affinity schemes); the reference serve
stack has no LLM-aware routing at all.

Routing policy, per request:

1. PREFIX AFFINITY — hash the first ``llm_router_prefix_tokens`` prompt
   tokens and rank replicas by rendezvous (highest-random-weight)
   hashing of (prefix_hash x replica actor id). All streams sharing a
   prefix agree on the same ranking without any shared state, and a
   replica joining/leaving only remaps the streams that hashed to it —
   no global reshuffle (the property consistent hashing buys).
2. OVERLOAD FALLBACK — affinity yields to load: if the preferred
   replica's pressure exceeds ``llm_router_overload_factor`` x the
   fleet mean, walk down the rendezvous ranking; if every replica is
   hot, take the least-pressured (pure load balancing).
   pressure = (router in-flight + engine pending) * (1 + busy), where
   busy is an EWMA of the replica's admit_s + decode_block_s rate from
   LLMServer.stats() — a replica spending all its wall time in
   admission/decode is saturated even at equal queue depth.
3. ADMISSION — a router-wide in-flight bound (``llm_router_max_inflight``)
   sheds excess demand with a typed 429 + Retry-After first frame
   instead of queueing unboundedly (same contract as LLMQueueFull at
   the engine).

Streaming failover: the router owns each replica stream and re-routes a
mid-stream replica death by resubmitting prompt + tokens-generated-so-far
(max_new_tokens decremented by the emitted count) to a surviving
replica. The client-visible stream continues with no duplicated or
dropped tokens — the resubmission's prompt IS the already-emitted
sequence, so the new replica only ever generates the continuation.

Stream-frame transport: with ``llm_router_compiled_hop`` (default on)
the router compiles one standing two-node graph per replica —
``InputNode -> replica.handle_request_streaming`` (dag/compiled.py) —
and each request is a raw channel enqueue; token frames ride the
standing channel back instead of paying per-call ``.remote()`` dispatch
plus a driver-mediated ref per frame. Replica death still surfaces as
``ActorDiedError`` from the frame iterator, feeding the same failover
path; compile failures fall back to the legacy per-call hop.
"""

from __future__ import annotations

import asyncio
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.serve.handle import DeploymentHandle, Router
from ray_tpu.util import metrics as _um
from ray_tpu.util.tracing import span

_END = object()


def _next_item(frames):
    """One blocking stream step (runs on an executor thread: raylint
    blocking-in-async). Raises the replica's ActorDiedError here when it
    died mid-stream — the async caller re-routes."""
    try:
        return next(frames)
    except StopIteration:
        return _END


def _legacy_frames(gen):
    """Frame iterator over the per-call dispatch path: each step submits
    nothing new but pulls the next streamed ObjectRef and resolves it."""
    while True:
        try:
            ref = next(gen)
        except StopIteration:
            return
        yield ray_tpu.get(ref)


def prefix_hash(tokens: List[int], n: int) -> str:
    """Stable cross-process hash of the first n prompt tokens."""
    head = ",".join(str(int(t)) for t in tokens[:n])
    return hashlib.sha1(head.encode()).hexdigest()


class LLMRouter:
    """Ingress deployment fronting an LLMServer deployment.

    Compose with serve.deployment + bind (see llm_deployment.build_llm_app):
    the LLMServer application is passed to bind() and arrives here as a
    DeploymentHandle; the router reads its replica set (long-poll pushed)
    through the handle's underlying Router but makes its OWN placement
    decisions.
    """

    def __init__(self, llm_handle: DeploymentHandle, *,
                 policy: str = "affinity",
                 prefix_tokens: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 overload_factor: Optional[float] = None,
                 stats_interval_s: Optional[float] = None,
                 report_load: bool = True,
                 max_attempts: int = 6,
                 compiled_hop: Optional[bool] = None,
                 tenant_weights: Optional[Dict[str, float]] = None):
        if policy not in ("affinity", "p2c", "random"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self._handle = llm_handle
        self.policy = policy
        cfg = GLOBAL_CONFIG
        # Weighted-fair tenant admission: explicit arg wins, else the
        # serve_tenant_weights JSON knob; unmapped tenants weigh 1.
        if tenant_weights is None and cfg.serve_tenant_weights:
            import json as _json
            try:
                tenant_weights = _json.loads(cfg.serve_tenant_weights)
            except Exception:
                tenant_weights = None
        self.tenant_weights: Dict[str, float] = {
            str(k): float(v) for k, v in (tenant_weights or {}).items()}
        self._compiled_hop = (compiled_hop if compiled_hop is not None
                              else cfg.llm_router_compiled_hop)
        #: replica key -> CompiledDAG of the standing stream-frame hop
        self._compiled: Dict[str, Any] = {}
        self.prefix_tokens = (prefix_tokens if prefix_tokens is not None
                              else cfg.llm_router_prefix_tokens)
        self.max_inflight = (max_inflight if max_inflight is not None
                             else cfg.llm_router_max_inflight)
        self.overload_factor = (overload_factor if overload_factor is not None
                                else cfg.llm_router_overload_factor)
        self._stats_interval = (stats_interval_s if stats_interval_s
                                is not None
                                else cfg.llm_router_stats_interval_s)
        self._report_load = report_load
        self.max_attempts = max_attempts
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}   # per-replica, router-local
        self._total_inflight = 0
        #: per-tenant / per-model in-flight splits of _total_inflight
        self._tenant_inflight: Dict[str, int] = {}
        self._model_inflight: Dict[str, int] = {}
        #: per-tenant admit/shed/TTFT aggregates (stats() + bench)
        self._tenant_stats: Dict[str, Dict[str, float]] = {}
        #: per-replica view from the stats poll thread:
        #: {pending, active, draining, busy, models, model_queue, ...}
        self._replica_stats: Dict[str, Dict[str, Any]] = {}
        self.counters = {"requests": 0, "shed": 0, "replica_shed": 0,
                         "replica_failed": 0, "tenant_shed": 0,
                         "reroutes": 0, "affinity_picks": 0,
                         "fallback_picks": 0, "warm_model_picks": 0,
                         "cold_model_picks": 0, "compiled_streams": 0,
                         "legacy_streams": 0}
        try:
            me = (ray_tpu.get_runtime_context().get_actor_id() or "driver")
        except Exception:
            me = "local"
        self._reporter = f"llm_router_{str(me)[:12]}"
        tag = {"router": self._reporter[-12:]}
        self._m_requests = _um.Counter(
            "ray_tpu_llm_router_requests", "requests routed",
            tag_keys=("router",)).set_default_tags(tag)
        self._m_sheds = _um.Counter(
            "ray_tpu_llm_router_sheds",
            "requests shed at the router admission bound",
            tag_keys=("router",)).set_default_tags(tag)
        self._m_reroutes = _um.Counter(
            "ray_tpu_llm_router_reroutes",
            "mid-stream failovers to a surviving replica",
            tag_keys=("router",)).set_default_tags(tag)
        self._m_affinity = _um.Counter(
            "ray_tpu_llm_router_affinity_picks",
            "placements on the rendezvous-preferred replica",
            tag_keys=("router",)).set_default_tags(tag)
        self._m_inflight = _um.Gauge(
            "ray_tpu_llm_router_inflight", "streams in flight",
            tag_keys=("router",)).set_default_tags(tag)
        self._m_ttft = _um.Histogram(
            "ray_tpu_llm_router_ttft_s",
            "router-observed time to first token",
            boundaries=[0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30],
            tag_keys=("router",)).set_default_tags(tag)
        # per-tenant telemetry: the tenant tag splits each series so the
        # dashboard/bench can see WHO was admitted, shed, and how slow
        self._m_tenant_requests = _um.Counter(
            "ray_tpu_serve_tenant_requests",
            "requests admitted per tenant",
            tag_keys=("router", "tenant")).set_default_tags(tag)
        self._m_tenant_sheds = _um.Counter(
            "ray_tpu_serve_tenant_sheds",
            "requests shed per tenant by weighted-fair admission",
            tag_keys=("router", "tenant")).set_default_tags(tag)
        self._m_tenant_ttft = _um.Histogram(
            "ray_tpu_serve_tenant_ttft_s",
            "per-tenant router-observed time to first token",
            boundaries=[0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30],
            tag_keys=("router", "tenant")).set_default_tags(tag)
        # Dedicated executor for blocking stream pulls: every in-flight
        # stream PARKS a thread in _next_item waiting for the replica's
        # next frame, so the event loop's small default pool would cap
        # concurrency at ~cpu+4 streams and stall the rest.
        import concurrent.futures

        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=min(self.max_inflight + 4, 512),
            thread_name_prefix="llm_router")
        self._stop = threading.Event()
        self._stats_thread = threading.Thread(target=self._stats_loop,
                                              daemon=True)
        self._stats_thread.start()

    # ---- replica view ------------------------------------------------------

    def _snapshot(self, force: bool = False) -> List[Tuple[str, Any]]:
        return self._snapshot_of(self._handle, force)

    @staticmethod
    def _snapshot_of(handle: DeploymentHandle,
                     force: bool = False) -> List[Tuple[str, Any]]:
        rt = handle._get_router()
        rt._ensure_poller()
        rt._refresh(force)
        with rt._lock:
            reps = list(rt._replicas)
        return [(Router._key(r), r) for r in reps]

    def _pressure(self, key: str) -> float:
        st = self._replica_stats.get(key, {})
        load = self._inflight.get(key, 0) + st.get("pending", 0)
        return load * (1.0 + st.get("busy", 0.0))

    def _poll_pool(self, handle: DeploymentHandle,
                   stats_map: Dict[str, Dict[str, Any]]) -> Optional[set]:
        """One stats sweep over a pool: poll each replica's stats(),
        fold the busy-fraction EWMA into stats_map, prune departed
        replicas. Returns the live key set (None: snapshot failed).
        Pool-generic so DisaggRouter reuses it for the prefill pool."""
        alpha = 0.5
        try:
            reps = self._snapshot_of(handle)
        except Exception:
            return None
        now = time.time()
        for key, replica in reps:
            try:
                raw = ray_tpu.get(
                    replica.handle_request.remote("stats", (), {}, None),
                    timeout=5)
            except Exception:
                continue   # dead replicas age out via the long-poll set
            busy_s = float(raw.get("admit_s", 0.0)) + \
                float(raw.get("decode_block_s", 0.0))
            with self._lock:
                prev = stats_map.get(key)
                frac = 0.0
                if prev is not None and now > prev["_ts"]:
                    frac = max(busy_s - prev["_raw_busy_s"], 0.0) \
                        / (now - prev["_ts"])
                ewma = (frac if prev is None
                        else alpha * frac + (1 - alpha) * prev["busy"])
                stats_map[key] = {
                    "pending": int(raw.get("pending", 0)),
                    "active": int(raw.get("active_slots", 0)),
                    "draining": bool(raw.get("draining", False)),
                    "busy": min(ewma, 4.0),
                    # advertised model set + per-model backlog from
                    # multiplexed replicas (absent -> single-model)
                    "models": list(raw.get("models") or []),
                    "model_queue": dict(raw.get("model_queue") or {}),
                    "_raw_busy_s": busy_s, "_ts": now,
                }
        with self._lock:
            live = {k for k, _ in reps}
            for k in list(stats_map):
                if k not in live:
                    del stats_map[k]
        return live

    def _report(self, deployment_name: str, depth: int,
                model_depths: Optional[Dict[str, int]] = None) -> None:
        """Push one pool's router-observed queue depth to the controller
        so autoscaling sees demand the replicas haven't accepted yet.
        model_depths carries the per-model split feeding the controller's
        per-model replica scaler."""
        if not self._report_load:
            return
        try:
            controller = ray_tpu.get_actor("_serve_controller",
                                           namespace="serve")
            ray_tpu.get(controller.report_load.remote(
                deployment_name, self._reporter, depth, model_depths),
                timeout=5)
        except Exception:
            pass   # controller restarting: next tick re-reports

    def _stats_loop(self):
        """Poll LLMServer.stats() per replica on a fixed cadence; derive
        the busy-fraction EWMA feeding the pressure score, and push the
        router's own queue depth to the controller so autoscaling sees
        demand the replicas haven't accepted yet."""
        while not self._stop.wait(self._stats_interval):
            self._stats_tick()

    def _stats_tick(self):
        live = self._poll_pool(self._handle, self._replica_stats)
        if live is None:
            return
        with self._lock:
            stale = [(k, c) for k, c in self._compiled.items()
                     if k not in live]
            for k, _ in stale:
                del self._compiled[k]
            depth = self._total_inflight
            mdepth = {m: v for m, v in self._model_inflight.items()
                      if v > 0}
        for _, comp in stale:   # off-lock: teardown RPCs block
            try:
                comp.teardown(kill_actors=False)
            except Exception:
                pass
        # always send the dict (even empty): a None would leave the
        # controller holding this reporter's LAST split for up to its
        # 10 s age-out, pinning per-model demand that already drained
        self._report(self._handle.deployment_name, depth, mdepth)

    # ---- placement ---------------------------------------------------------

    def _pick(self, prompt: List[int], model: str,
              avoid: set) -> Tuple[str, Any]:
        """Choose a replica (blocking; call from an executor thread).
        avoid = replicas that already shed this request. The rendezvous
        key is (model_id, prefix): all traffic for one model converges on
        the same sub-ranking, and within it shared prefixes converge
        further. Replicas ADVERTISING the model (loaded + published) are
        stably promoted ahead of cold ones so the overload walk prefers
        paying queueing over paying a model load."""
        import random

        reps = self._snapshot()
        if not reps:
            reps = self._snapshot(force=True)
        with self._lock:
            stats = dict(self._replica_stats)
        usable = [(k, r) for k, r in reps
                  if k not in avoid
                  and not stats.get(k, {}).get("draining", False)]
        if not usable:
            # every replica draining/avoided: last resort is the raw set
            usable = [(k, r) for k, r in reps if k not in avoid]
        if not usable:
            raise RuntimeError(
                f"no usable replicas for {self._handle.deployment_name!r}")
        with span("llm_router.route", {"policy": self.policy,
                                       "n_replicas": len(usable)}):
            if self.policy == "random" or len(usable) == 1:
                return usable[random.randrange(len(usable))]
            if self.policy == "p2c":
                a, b = random.sample(range(len(usable)), 2)
                ka, kb = usable[a][0], usable[b][0]
                return usable[a if self._pressure(ka)
                              <= self._pressure(kb) else b]
            ph = prefix_hash(prompt, self.prefix_tokens)
            rkey = f"{model}\x00{ph}" if model else ph
            ranked = sorted(
                usable, key=lambda kr: hashlib.sha1(
                    f"{rkey}:{kr[0]}".encode()).digest(), reverse=True)
            if model:
                # stable warm-first partition (rendezvous order kept
                # within each half): a replica with the model resident
                # skips the load entirely
                warm_keys = {k for k, _ in ranked
                             if model in (stats.get(k, {}).get("models")
                                          or [])}
                if warm_keys:
                    ranked = ([kr for kr in ranked if kr[0] in warm_keys]
                              + [kr for kr in ranked
                                 if kr[0] not in warm_keys])
            else:
                warm_keys = set()
            mean = sum(self._pressure(k) for k, _ in usable) / len(usable)
            limit = self.overload_factor * max(mean, 1.0)
            chosen = None
            chosen_rank = 0
            for rank, (k, r) in enumerate(ranked):
                if self._pressure(k) <= limit:
                    chosen, chosen_rank = (k, r), rank
                    break
            if chosen is None:
                chosen = min(ranked, key=lambda kr: self._pressure(kr[0]))
                chosen_rank = -1
            with self._lock:
                if chosen_rank == 0:
                    self.counters["affinity_picks"] += 1
                else:
                    self.counters["fallback_picks"] += 1
                if model:
                    if chosen[0] in warm_keys:
                        self.counters["warm_model_picks"] += 1
                    else:
                        self.counters["cold_model_picks"] += 1
            if chosen_rank == 0:
                self._m_affinity.inc()
            return chosen

    # ---- weighted-fair tenant admission ------------------------------------

    def _tenant_weight(self, tenant: str) -> float:
        return float(self.tenant_weights.get(tenant, 1.0))

    def _tenant_share_locked(self, tenant: str) -> float:
        """`tenant`'s guaranteed slice of max_inflight: weights are
        normalized over the tenants ACTIVE right now (plus the asker),
        so idle tenants do not strand capacity. Caller holds _lock."""
        active = {t for t, v in self._tenant_inflight.items() if v > 0}
        active.add(tenant)
        wsum = sum(self._tenant_weight(t) for t in active)
        return self.max_inflight * self._tenant_weight(tenant) \
            / max(wsum, 1e-9)

    def _admit_locked(self, tenant: str) -> bool:
        """Weighted-fair queuing over in-flight shares. A tenant within
        its guaranteed share ALWAYS admits — even with the global cap
        transiently exceeded by another tenant's borrowing (overshoot is
        bounded by the sum of guaranteed shares = max_inflight). Past
        its share, a tenant may only borrow idle capacity under the
        global cap — so when the router saturates, the most-over-quota
        tenant is exactly the one shed first."""
        cur = self._tenant_inflight.get(tenant, 0)
        if cur + 1 <= self._tenant_share_locked(tenant):
            return True
        return self._total_inflight < self.max_inflight

    def _tenant_stat(self, tenant: str) -> Dict[str, float]:
        return self._tenant_stats.setdefault(
            tenant, {"requests": 0, "shed": 0,
                     "ttft_sum": 0.0, "ttft_count": 0})

    # ---- request paths -----------------------------------------------------

    async def stream_request(self, request) -> Any:
        """End-to-end streaming entry (HTTP ?stream=1 / SSE, or handle
        calls): weighted-fair admission -> model/prefix placement -> fan
        the replica's token frames through, surviving replica death
        mid-stream by re-routing with prompt + generated-so-far. The
        model id and tenant tag come from the body ("model"/"tenant")
        or, for handle calls via .options(), the call context."""
        from ray_tpu.serve.multiplex import (get_multiplexed_model_id,
                                             get_request_tenant)
        body = request if isinstance(request, dict) else request.json()
        prompt = list(body["prompt"])
        max_new = int(body.get("max_new_tokens", 32))
        temperature = float(body.get("temperature", 0.0))
        model = str(body.get("model") or get_multiplexed_model_id() or "")
        tenant = str(body.get("tenant") or get_request_tenant()
                     or "default")
        with self._lock:
            if not self._admit_locked(tenant):
                self.counters["shed"] += 1
                self.counters["tenant_shed"] += 1
                self._tenant_stat(tenant)["shed"] += 1
                shed = True
            else:
                self._total_inflight += 1
                self._tenant_inflight[tenant] = \
                    self._tenant_inflight.get(tenant, 0) + 1
                if model:
                    self._model_inflight[model] = \
                        self._model_inflight.get(model, 0) + 1
                self.counters["requests"] += 1
                self._tenant_stat(tenant)["requests"] += 1
                shed = False
            self._m_inflight.set(self._total_inflight)
        if shed:
            self._m_sheds.inc()
            self._m_tenant_sheds.inc(tags={"tenant": tenant})
            yield {"error": f"tenant {tenant!r} over fair share at "
                            f"max_inflight={self.max_inflight}; "
                            "retry later",
                   "status": 429, "retry_after_s": 1.0, "done": True}
            return
        self._m_requests.inc()
        self._m_tenant_requests.inc(tags={"tenant": tenant})
        ctx = ({"multiplexed_model_id": model, "tenant": tenant}
               if (model or tenant != "default") else None)
        loop = asyncio.get_running_loop()
        t0 = time.time()
        first_t: Optional[float] = None
        emitted: List[int] = []
        avoid: set = set()
        attempts = 0
        last_err: Optional[str] = None
        try:
            while True:
                attempts += 1
                if attempts > self.max_attempts:
                    yield {"error": last_err
                                    or "no replica could finish the stream",
                           "status": 503, "done": True,
                           "n_tokens": len(emitted)}
                    return
                try:
                    key, replica = await loop.run_in_executor(
                        self._executor, self._pick, prompt, model, avoid)
                except RuntimeError as e:
                    yield {"error": (f"{e}; last replica error: {last_err}"
                                     if last_err else str(e)),
                           "status": 503, "done": True,
                           "n_tokens": len(emitted)}
                    return
                sub = {"prompt": prompt + emitted,
                       "max_new_tokens": max_new - len(emitted),
                       "temperature": temperature}
                if model:
                    sub["model"] = model
                if tenant != "default":
                    sub["tenant"] = tenant
                with self._lock:
                    self._inflight[key] = self._inflight.get(key, 0) + 1
                rerouted = False
                try:
                    frames = await loop.run_in_executor(
                        self._executor, self._open_stream, key, replica,
                        (sub,), "stream_request", ctx)
                    while True:
                        try:
                            item = await loop.run_in_executor(
                                self._executor, _next_item, frames)
                        except (ray_tpu.exceptions.ActorDiedError,
                                ray_tpu.exceptions.ActorUnavailableError
                                ) as e:
                            self._on_replica_death(key, e)
                            rerouted = True
                            break
                        if item is _END:
                            # clean end without a done frame (defensive)
                            yield self._final(emitted, first_t, t0,
                                              attempts, key)
                            return
                        if isinstance(item, dict) and \
                                item.get("status") == 429:
                            # replica shed (queue full or draining):
                            # route around it, do not fail the client
                            with self._lock:
                                self.counters["replica_shed"] += 1
                            avoid.add(key)
                            rerouted = True
                            break
                        if isinstance(item, dict) and item.get("done") \
                                and int(item.get("status") or 0) >= 500:
                            # replica-side hard failure (e.g. cold-model
                            # load failed): another replica may still
                            # serve it — route around, fail the client
                            # only when every attempt is spent
                            with self._lock:
                                self.counters["replica_failed"] += 1
                            last_err = item.get("error")
                            avoid.add(key)
                            rerouted = True
                            break
                        if isinstance(item, dict) and item.get("done"):
                            out = self._final(emitted, first_t, t0,
                                              attempts, key)
                            if item.get("error"):
                                out["error"] = item["error"]
                            yield out
                            return
                        toks = (item or {}).get("tokens", [])
                        if toks:
                            if first_t is None:
                                first_t = time.time()
                                ttft = first_t - t0
                                self._m_ttft.observe(ttft)
                                self._m_tenant_ttft.observe(
                                    ttft, tags={"tenant": tenant})
                                with self._lock:
                                    st = self._tenant_stat(tenant)
                                    st["ttft_sum"] += ttft
                                    st["ttft_count"] += 1
                            emitted.extend(toks)
                            yield {"tokens": toks}
                finally:
                    with self._lock:
                        if self._inflight.get(key, 0) > 0:
                            self._inflight[key] -= 1
                if not rerouted:
                    return
        finally:
            with self._lock:
                self._total_inflight = max(self._total_inflight - 1, 0)
                # drop zeroed entries: the split dicts stay bounded by
                # ACTIVE tenants/models, not the lifetime catalog
                if self._tenant_inflight.get(tenant, 0) > 1:
                    self._tenant_inflight[tenant] -= 1
                else:
                    self._tenant_inflight.pop(tenant, None)
                if model:
                    if self._model_inflight.get(model, 0) > 1:
                        self._model_inflight[model] -= 1
                    else:
                        self._model_inflight.pop(model, None)
                self._m_inflight.set(self._total_inflight)

    # ---- stream transport --------------------------------------------------

    def _open_stream(self, key: str, replica, args: tuple,
                     method: str = "stream_request",
                     context: Optional[dict] = None):
        """Open one replica stream (blocking; executor thread). Compiled
        hop when enabled: a raw enqueue onto the replica's standing
        channel; otherwise the per-call dispatch path. The method is an
        execute-time input on the standing graph, so the SAME channel
        per replica carries any streaming method — stream_request for
        the monolithic pool, adopt_decode for the disagg decode hop.
        `context` (multiplexed_model_id / tenant) is an execute-time
        input too, so BOTH hops deliver identical per-call context to
        the replica's contextvars."""
        if self._compiled_hop:
            try:
                comp = self._compiled_for(key, replica)
                ref = comp.execute(method=method, args=args,
                                   kwargs={}, context=context)
                with self._lock:
                    self.counters["compiled_streams"] += 1
                return iter(ref)
            except (ray_tpu.exceptions.ActorDiedError,
                    ray_tpu.exceptions.ActorUnavailableError):
                raise
            except Exception:
                # compile/enqueue failure that is NOT the replica dying:
                # drop the graph and serve via the legacy hop
                self._drop_compiled(key)
        with self._lock:
            self.counters["legacy_streams"] += 1
        gen = replica.handle_request_streaming.remote(
            method, args, {}, context)
        return _legacy_frames(gen)

    def _compiled_for(self, key: str, replica):
        with self._lock:
            comp = self._compiled.get(key)
        if comp is not None:
            return comp
        from ray_tpu.dag import InputNode, bind_actor

        with InputNode() as inp:
            dag = bind_actor(replica).handle_request_streaming.bind(
                inp.method, inp.args, inp.kwargs, inp.context)
        comp = dag.experimental_compile()
        with self._lock:
            racing = self._compiled.get(key)
            if racing is not None:
                comp_, comp = comp, racing
            else:
                self._compiled[key] = comp
                comp_ = None
        if comp_ is not None:
            comp_.teardown(kill_actors=False)
        return comp

    def _drop_compiled(self, key: str) -> None:
        """Release a replica's standing channel off the event loop (the
        teardown RPCs block)."""
        with self._lock:
            comp = self._compiled.pop(key, None)
        if comp is not None:
            try:
                self._executor.submit(comp.teardown, False)
            except RuntimeError:
                pass   # executor already shut down (drain)

    def _on_replica_death(self, key: str, err) -> None:
        """Mid-stream death: evict from the shared replica view so no
        request (ours included) re-picks the corpse, then account the
        re-route. The in-flight decrement rides the attempt's finally —
        the leak the old index-keyed Router had."""
        rt = self._handle._get_router()
        rt.evict(getattr(err, "actor_id", None) or key)
        self._drop_compiled(key)
        with self._lock:
            self._replica_stats.pop(key, None)
            self.counters["reroutes"] += 1
        self._m_reroutes.inc()

    def _final(self, emitted, first_t, t0, attempts, key) -> Dict[str, Any]:
        return {"done": True, "n_tokens": len(emitted),
                "ttft_s": (first_t - t0) if first_t is not None else None,
                "reroutes": attempts - 1, "replica": key[:12]}

    async def __call__(self, request) -> Any:
        """Non-streaming entry: same routing/failover machinery, result
        collected. 429s map to Response(429, Retry-After) for the proxy."""
        body = request if isinstance(request, dict) else request.json()
        tokens: List[int] = []
        final: Dict[str, Any] = {}
        async for frame in self.stream_request(body):
            if frame.get("status") == 429:
                from ray_tpu.serve.http_proxy import Response

                retry = frame.get("retry_after_s", 1.0)
                return Response({"error": frame.get("error")},
                                status_code=429,
                                headers={"Retry-After": f"{retry:g}"})
            if frame.get("done"):
                final = frame
            tokens.extend(frame.get("tokens", []))
        if final.get("error"):
            from ray_tpu.serve.http_proxy import Response

            return Response({"error": final["error"]},
                            status_code=int(final.get("status", 500)))
        return {"tokens": tokens, "ttft_s": final.get("ttft_s"),
                "reroutes": final.get("reroutes", 0)}

    # ---- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {**dict(self.counters),
                    "policy": self.policy,
                    "total_inflight": self._total_inflight,
                    "inflight": dict(self._inflight),
                    "tenant_weights": dict(self.tenant_weights),
                    "tenant_inflight": dict(self._tenant_inflight),
                    "model_inflight": dict(self._model_inflight),
                    "tenant_stats": {t: dict(v) for t, v in
                                     self._tenant_stats.items()},
                    "replica_stats": {
                        k: {kk: vv for kk, vv in v.items()
                            if not kk.startswith("_")}
                        for k, v in self._replica_stats.items()}}

    def queue_len(self) -> int:
        with self._lock:
            return self._total_inflight

    def drain(self) -> None:
        """Router replica retiring: stop the stats thread and release the
        standing channels; in-flight streams keep running (the controller
        waits on queue_len)."""
        self._stop.set()
        with self._lock:
            comps = list(self._compiled.values())
            self._compiled.clear()
        for comp in comps:
            try:
                self._executor.submit(comp.teardown, False)
            except RuntimeError:
                pass
        self._executor.shutdown(wait=False)
