"""Per-node dashboard agent.

Reference: dashboard/agent.py + the reporter module
(dashboard/modules/reporter/) — a per-node collector that samples host
and runtime stats and PUSHES them to the control plane, so the head
aggregates from one place instead of fanning RPCs out to every node on
every request (the round-1 head did exactly that fan-out, which cannot
scale past tens of nodes).

Here the agent is an asyncio task inside the nodelet process (one fewer
process per node; the nodelet is already supervised and Python), sampling
every `metrics_report_interval_s` and writing to GCS KV ns="node_stats".
The head's /api/v0/node_stats is then a single KV scan. A standalone
entry point (`python -m ray_tpu.dashboard.agent`) exists for running the
agent out-of-process against any nodelet, mirroring the reference's
separate-agent deployment.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

_CLK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


def sample_host() -> Dict[str, Any]:
    """Host-level stats from /proc (no psutil in-image; ref: the
    reporter's cpu/mem/disk sampling)."""
    out: Dict[str, Any] = {"time": time.time()}
    try:
        with open("/proc/loadavg") as f:
            parts = f.read().split()
            out["load_1m"] = float(parts[0])
            out["load_5m"] = float(parts[1])
    except OSError:
        pass
    try:
        mem = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                mem[k] = int(rest.split()[0]) * 1024   # kB -> bytes
        out["mem_total"] = mem.get("MemTotal", 0)
        out["mem_available"] = mem.get("MemAvailable", 0)
    except OSError:
        pass
    try:
        with open("/proc/stat") as f:
            cpu = f.readline().split()[1:8]
        vals = [int(v) for v in cpu]
        out["cpu_jiffies_total"] = sum(vals)
        out["cpu_jiffies_idle"] = vals[3]
    except OSError:
        pass
    try:
        st = os.statvfs("/")
        out["disk_free"] = st.f_bavail * st.f_frsize
        out["disk_total"] = st.f_blocks * st.f_frsize
    except OSError:
        pass
    return out


async def agent_tick(get_stats, kv_put) -> dict:
    """One sample: runtime stats (from `get_stats()` — in-process
    rpc_node_stats or a remote node_stats call) + host stats, pushed to
    GCS KV under the node id."""
    stats = await get_stats()
    stats["host"] = sample_host()
    stats["collected_at"] = time.time()
    nid = stats["node_id"]
    key = nid.binary() if hasattr(nid, "binary") else bytes.fromhex(str(nid))
    stats["node_id"] = nid.hex() if hasattr(nid, "hex") else str(nid)
    await kv_put("node_stats", key,
                 json.dumps(stats, default=str).encode())
    return stats


async def run_agent(nodelet, gcs_call_async, interval_s: float,
                    stop_fn=None):
    """The nodelet-embedded loop; gcs_call_async(method, **kw) awaits a
    GCS RPC; stop_fn() -> True ends the loop."""
    import asyncio

    async def kv_put(ns, key, value):
        await gcs_call_async("kv_put", ns=ns, key=key, value=value,
                             overwrite=True)

    while not (stop_fn is not None and stop_fn()):
        try:
            await agent_tick(nodelet.rpc_node_stats, kv_put)
        except asyncio.CancelledError:
            raise
        except Exception:   # noqa: BLE001 — sampling must never kill the node
            pass
        await asyncio.sleep(interval_s)


def main():
    """Standalone agent: attach to a nodelet + GCS from outside
    (reference-parity separate-process deployment)."""
    import argparse
    import asyncio

    from ray_tpu.core.rpc import ClientPool

    ap = argparse.ArgumentParser()
    ap.add_argument("--gcs", required=True)
    ap.add_argument("--nodelet", required=True)
    ap.add_argument("--interval", type=float, default=5.0)
    args = ap.parse_args()

    async def run():
        pool = ClientPool()
        gh, gp = args.gcs.rsplit(":", 1)
        nh, np_ = args.nodelet.rsplit(":", 1)
        gcs = pool.get((gh, int(gp)))
        nodelet = pool.get((nh, int(np_)))

        async def get_stats():
            return await nodelet.call("node_stats", timeout=5.0)

        async def kv_put(ns, key, value):
            await gcs.call("kv_put", ns=ns, key=key, value=value,
                           overwrite=True, timeout=5.0)

        while True:
            try:
                await agent_tick(get_stats, kv_put)
            except Exception:   # noqa: BLE001
                pass
            await asyncio.sleep(args.interval)

    asyncio.run(run())


if __name__ == "__main__":
    main()
