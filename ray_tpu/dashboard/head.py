"""Dashboard head server.

Reference: dashboard/head.py — an aiohttp server on the head node serving
pluggable modules (dashboard/utils.py:40 DashboardHeadModule); we fold the
state/metrics/jobs/logs modules into route groups on one app. Talks to the
GCS directly over the RPC layer (no driver Runtime required), like the
reference head's GcsClient.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core.rpc import ClientPool

Address = Tuple[str, int]


def _jsonable(v: Any):
    """Best-effort conversion of dataclasses / ids / bytes for JSON."""
    if isinstance(v, dict):
        return {_jsonable_key(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, bytes):
        return v.hex()
    if hasattr(v, "hex") and not isinstance(v, (int, float)):
        try:
            return v.hex()
        except TypeError:
            pass
    if hasattr(v, "__dataclass_fields__"):
        return {f: _jsonable(getattr(v, f)) for f in v.__dataclass_fields__}
    if hasattr(v, "quantities"):
        return _jsonable(v.quantities)
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def _jsonable_key(k: Any):
    if isinstance(k, bytes):
        return k.hex()
    if isinstance(k, (str, int, float, bool)):
        return k
    return str(k)


# Single-file UI (ref: dashboard/client — a React SPA there; here a
# dependency-free vanilla-JS app served inline, the right weight for a
# TPU fleet console: summary cards, node/actor/job tables, auto-refresh,
# raw API links). No build step, no npm, works from the aiohttp head.
_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:ui-monospace,Menlo,monospace;margin:1.2rem;background:#101418;color:#d6dde4}
h2{margin:0 0 .8rem}  a{color:#6ab0f3}
.cards{display:flex;gap:.8rem;flex-wrap:wrap;margin-bottom:1rem}
.card{background:#1a2129;border:1px solid #2a333d;border-radius:6px;padding:.7rem 1rem;min-width:8.5rem}
.card b{display:block;font-size:1.4rem}  .card span{color:#8b98a5;font-size:.8rem}
table{border-collapse:collapse;width:100%;margin-bottom:1.2rem;font-size:.85rem}
th,td{border-bottom:1px solid #2a333d;padding:.3rem .6rem;text-align:left}
th{color:#8b98a5;font-weight:600}  .dead{color:#e66}  .alive{color:#7c6}
#err{color:#e66}  footer{color:#8b98a5;font-size:.8rem}
</style></head><body>
<h2>ray_tpu dashboard</h2>
<div class="cards" id="cards"></div>
<h3>nodes</h3><table id="nodes"><thead><tr>
<th>node</th><th>state</th><th>resources</th><th>store</th><th>load</th><th>mem free</th><th>workers</th></tr></thead><tbody></tbody></table>
<h3>actors</h3><table id="actors"><thead><tr>
<th>actor</th><th>class</th><th>state</th><th>name</th><th>restarts</th></tr></thead><tbody></tbody></table>
<h3>jobs</h3><table id="jobs"><thead><tr>
<th>job</th><th>started</th><th>ended</th></tr></thead><tbody></tbody></table>
<h3>tasks</h3><table id="tasks"><thead><tr>
<th>task</th><th>name</th><th>state</th><th>worker</th><th>duration</th></tr></thead><tbody></tbody></table>
<h3>timeline <span style="color:#8b98a5;font-size:.8rem">(one lane per worker, last 60 s window of finished tasks + spans)</span></h3>
<canvas id="tl" width="1100" height="160" style="background:#1a2129;border:1px solid #2a333d;border-radius:6px"></canvas>
<div id="err"></div>
<footer>raw: <a href="/api/v0/summary">summary</a> · <a href="/api/v0/nodes">nodes</a>
· <a href="/api/v0/actors">actors</a> · <a href="/api/v0/tasks">tasks</a>
· <a href="/api/v0/jobs">jobs</a> · <a href="/api/v0/node_stats">node stats</a>
· <a href="/metrics">prometheus</a> · <a href="/api/v0/logs">logs</a>
&nbsp;|&nbsp; refreshes every 5 s</footer>
<script>
const fmtB=(b)=>b>1<<30?(b/2**30).toFixed(1)+"G":b>1<<20?(b/2**20).toFixed(0)+"M":b+"B";
const cell=(t)=>{const td=document.createElement("td");td.textContent=t??"";return td};
async function j(u){const r=await fetch(u);if(!r.ok)throw new Error(u+": "+r.status);return r.json()}
async function tick(){
 try{
  const [sum,nodes,actors,jobs,stats]=await Promise.all([
    j("/api/v0/summary"),j("/api/v0/nodes"),j("/api/v0/actors"),
    j("/api/v0/jobs"),j("/api/v0/node_stats")]);
  const cards=[["nodes alive",sum.nodes_alive],["nodes dead",sum.nodes_dead],
    ["actors alive",sum.actors_alive+"/"+sum.actors_total],
    ...Object.entries(sum.total_resources||{}).map(([k,v])=>[k,v])];
  document.getElementById("cards").replaceChildren(...cards.map(([k,v])=>{
    const d=document.createElement("div");d.className="card";
    const b=document.createElement("b");b.textContent=v;
    const s=document.createElement("span");s.textContent=k;
    d.append(b,s);return d}));
  const nb=document.querySelector("#nodes tbody");nb.replaceChildren();
  for(const n of nodes){const st=stats[n.node_id]||{};const h=st.host||{};
    const tr=document.createElement("tr");
    const state=cell(n.alive?"ALIVE":"DEAD");state.className=n.alive?"alive":"dead";
    tr.append(cell(n.node_id.slice(0,12)),state,
      cell(Object.entries(n.resources).map(([k,v])=>k+":"+v).join(" ")),
      cell(st.store_bytes!=null?fmtB(st.store_bytes)+" / "+(st.store_objects??"?")+" obj":"-"),
      cell(h.load_1m!=null?h.load_1m.toFixed(2):"-"),
      cell(h.mem_available!=null?fmtB(h.mem_available):"-"),
      cell(st.workers?Object.keys(st.workers).length:"-"));
    nb.append(tr)}
  const ab=document.querySelector("#actors tbody");ab.replaceChildren();
  for(const a of actors.slice(0,200)){const tr=document.createElement("tr");
    const state=cell(a.state);state.className=a.state==="ALIVE"?"alive":(a.state==="DEAD"?"dead":"");
    tr.append(cell((a.actor_id||"").slice(0,12)),cell(a.class_name),state,
      cell(a.name||""),cell(a.num_restarts));ab.append(tr)}
  const jb=document.querySelector("#jobs tbody");jb.replaceChildren();
  for(const job of jobs.slice(0,100)){const tr=document.createElement("tr");
    tr.append(cell((job.job_id||"").slice(0,12)),
      cell(job.start?new Date(job.start*1000).toLocaleTimeString():""),
      cell(job.end?new Date(job.end*1000).toLocaleTimeString():"running"));
    jb.append(tr)}
  const tsum=await j("/api/v0/task_summary?limit=2000");
  const tb=document.querySelector("#tasks tbody");tb.replaceChildren();
  for(const t of tsum.tasks.slice(0,200)){const tr=document.createElement("tr");
    const st=cell(t.state);st.className=t.state==="FINISHED"?"alive":(t.state==="FAILED"?"dead":"");
    tr.append(cell((t.task_id||"").slice(0,12)),cell(t.name),st,
      cell(t.worker||"-"),
      cell(t.duration_s!=null?(t.duration_s*1000).toFixed(1)+" ms":"-"));
    tb.append(tr)}
  drawTimeline(tsum);
  document.getElementById("err").textContent="";
 }catch(e){document.getElementById("err").textContent=String(e)}
}
function drawTimeline(tsum){
 // bars come straight from the summary rows (start/end/worker already
 // paired server-side) + tracing spans; one lane per worker
 const cv=document.getElementById("tl"),ctx=cv.getContext("2d");
 ctx.clearRect(0,0,cv.width,cv.height);
 const now=Date.now()/1000,w0=now-60;
 const bars=[];
 for(const ev of tsum.spans||[]){
  if(ev.ts>w0)bars.push({lane:"span:"+String(ev.trace_id).slice(0,6),
    t0:ev.ts,t1:ev.ts+(ev.dur||0),name:ev.name,span:true})}
 for(const t of tsum.tasks||[]){
  if(t.start_ts!=null&&t.end_ts!=null&&t.end_ts>w0)
   bars.push({lane:t.worker||"?",t0:t.start_ts,t1:t.end_ts,
     name:t.name,fail:t.state==="FAILED"})}
 const lanes=[...new Set(bars.map(b=>b.lane))].sort();
 const lh=Math.min(26,Math.max(14,(cv.height-18)/Math.max(lanes.length,1)));
 ctx.font="10px ui-monospace";
 lanes.forEach((ln,i)=>{ctx.fillStyle="#8b98a5";
   ctx.fillText(ln,4,14+i*lh)});
 const x=(t)=>90+(t-w0)/60*(cv.width-100);
 for(const b of bars){const i=lanes.indexOf(b.lane);
  ctx.fillStyle=b.span?"#c9a227":(b.fail?"#e66":"#4f9d69");
  const x0=Math.max(90,x(b.t0));
  ctx.fillRect(x0,6+i*lh,Math.max(x(b.t1)-x0,2),lh-6)}
 ctx.fillStyle="#8b98a5";
 ctx.fillText("-60s",92,cv.height-4);ctx.fillText("now",cv.width-30,cv.height-4);
}
tick();setInterval(tick,5000);
</script></body></html>"""


class DashboardHead:
    def __init__(self, gcs_addr: Address, session_dir: str = "",
                 host: str = "127.0.0.1", port: int = 8265):
        self.gcs_addr = tuple(gcs_addr)
        self.session_dir = session_dir
        self.host = host
        self.port = port
        self.pool = ClientPool()
        self._runner = None
        self._site = None

    async def _gcs(self, method: str, **kw):
        return await self.pool.get(self.gcs_addr).call(method, timeout=10.0, **kw)

    # ------------------------------------------------------------- handlers

    async def _h_index(self, request):
        from aiohttp import web

        return web.Response(text=_INDEX_HTML, content_type="text/html")

    def _json(self, payload):
        from aiohttp import web

        return web.json_response(_jsonable(payload))

    async def _h_nodes(self, request):
        nodes = await self._gcs("get_nodes")
        return self._json([{
            "node_id": n.node_id.hex(), "alive": n.alive,
            "address": list(n.nodelet_addr),
            "resources": n.resources_total.quantities,
            "labels": n.labels, "store_name": n.store_name,
        } for n in nodes])

    async def _h_actors(self, request):
        return self._json(await self._gcs("list_actors"))

    async def _h_edge_stats(self, request):
        """Measured per-edge transfer model (EWMA latency/bandwidth per
        src->dst node pair), fed by batched telemetry reports."""
        return self._json(await self._gcs("edge_stats"))

    async def _h_health(self, request):
        """Health plane: progress beacons with freshness, recent stall /
        straggler events, drop counters (observability/health.py)."""
        return self._json(await self._gcs("health_report"))

    async def _h_memory(self, request):
        """Memory plane: per-subsystem attribution, top holders, spill
        candidates, leak suspects (observability/memory.py)."""
        top_n = int(request.query.get("top_n", 20))
        return self._json(await self._gcs("memory_report", top_n=top_n))

    async def _h_tasks(self, request):
        limit = int(request.query.get("limit", 1000))
        return self._json(await self._gcs("list_task_events", limit=limit))

    async def _h_task_summary(self, request):
        """Per-task drill-down rows + tracing spans (ref: dashboard task
        table, dashboard/modules/state/state_head.py): latest state,
        start time, duration, worker — aggregated from the GCS
        task-event store. One payload feeds both the UI's task table and
        its timeline (a single GCS read per refresh tick)."""
        limit = int(request.query.get("limit", 2000))
        events = await self._gcs("list_task_events", limit=limit)
        spans = [ev for ev in events if ev.get("kind") == "span"]
        # Fold into a PERSISTENT per-task cache: the GCS store keeps only
        # the newest `limit` events, so a long-running task's RUNNING
        # event can age out while its FINISHED remains — folding only the
        # current window would then yield FINISHED rows with null
        # start_ts/duration. Re-folding the same event is idempotent, so
        # the cache just accumulates the newest window each tick.
        tasks: Dict[str, dict] = getattr(self, "_task_rows", None) or {}
        self._task_rows = tasks
        # events from different processes flush independently and
        # interleave out of order in the GCS — fold by timestamp, or a
        # late-arriving PENDING overwrites a FINISHED forever
        for ev in sorted((ev for ev in events if ev.get("kind") != "span"),
                         key=lambda ev: ev["ts"]):
            t = tasks.setdefault(ev["task_id"], {
                "task_id": ev["task_id"], "name": ev.get("name"),
                "actor_id": ev.get("actor_id"), "worker": None,
                "state": None, "start_ts": None, "end_ts": None,
                "duration_s": None, "_last_ts": 0.0})
            if ev["ts"] < t["_last_ts"]:
                continue   # older than what's already folded for this task
            t["_last_ts"] = ev["ts"]
            t["state"] = ev.get("state")
            if ev.get("worker"):
                t["worker"] = ev["worker"]
            if ev.get("state") == "RUNNING":
                t["start_ts"] = ev["ts"]
            elif ev.get("state") in ("FINISHED", "FAILED"):
                t["end_ts"] = ev["ts"]
                if t["start_ts"] is not None:
                    t["duration_s"] = ev["ts"] - t["start_ts"]
        # bound the cache: evict oldest FINISHED/FAILED first, then (if a
        # churning cluster left terminal-less rows — e.g. a SIGKILLed
        # worker never flushed its FINISHED span) oldest rows of ANY
        # state, so the cache cannot grow without bound
        cap = 10000
        if len(tasks) > cap:
            by_age = sorted(tasks.values(), key=lambda t: t["_last_ts"])
            terminal = [t for t in by_age
                        if t["state"] in ("FINISHED", "FAILED")]
            rest = [t for t in by_age
                    if t["state"] not in ("FINISHED", "FAILED")]
            for t in (terminal + rest)[:len(tasks) - cap]:
                tasks.pop(t["task_id"], None)
        out = sorted(tasks.values(),
                     key=lambda t: t.get("start_ts") or 0, reverse=True)
        out = [{k: v for k, v in t.items() if k != "_last_ts"}
               for t in out[:limit]]   # honor ?limit= on the response too
        return self._json({"tasks": out, "spans": spans})

    async def _h_jobs(self, request):
        return self._json(await self._gcs("list_jobs"))

    # ------------------------------------------------ job submission REST
    # (ref: dashboard/modules/job/job_head.py — POST /api/jobs/,
    # GET /api/jobs/{id}, logs, stop; the SDK's http mode targets these)

    def _job_client(self):
        """Lazy driver connection for actor-backed job supervision (the
        reference job head holds a JobManager the same way). Runs on the
        executor thread — ray_tpu.init can block for the full connect
        timeout and must never stall the dashboard's event loop."""
        if getattr(self, "_jobs", None) is None:
            import ray_tpu
            from ray_tpu.job.manager import JobSubmissionClient

            if not ray_tpu.is_initialized():
                ray_tpu.init(
                    address=f"{self.gcs_addr[0]}:{self.gcs_addr[1]}")
            self._jobs = JobSubmissionClient()
        return self._jobs

    async def _job_call(self, method: str, *args, **kw):
        """Resolve the client AND run the named method on the executor —
        nothing ray-blocking touches the event loop."""
        loop = asyncio.get_running_loop()

        def run():
            return getattr(self._job_client(), method)(*args, **kw)

        return await loop.run_in_executor(None, run)

    async def _h_job_submit(self, request):
        from aiohttp import web

        body = await request.json()
        if "entrypoint" not in body:
            return web.json_response(
                {"error": "missing 'entrypoint'"}, status=400)
        try:
            job_id = await self._job_call(
                "submit_job", entrypoint=body["entrypoint"],
                runtime_env=body.get("runtime_env"),
                working_dir=body.get("working_dir"),
                submission_id=body.get("submission_id"))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)
        return self._json({"job_id": job_id, "submission_id": job_id})

    async def _h_job_list(self, request):
        """Submission-API jobs (KV-backed), distinct from the cluster
        driver-jobs table at /api/v0/jobs (ref: job_head.py list)."""
        from aiohttp import web

        try:
            jobs = await self._job_call("list_jobs")
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)
        return self._json(jobs)

    async def _h_job_info(self, request):
        from aiohttp import web

        try:
            info = await self._job_call("get_job_info",
                                        request.match_info["job_id"])
        except Exception as e:
            return web.json_response({"error": str(e)}, status=404)
        return self._json(info)

    async def _h_job_logs(self, request):
        from aiohttp import web

        try:
            logs = await self._job_call("get_job_logs",
                                        request.match_info["job_id"])
        except Exception as e:
            return web.json_response({"error": str(e)}, status=404)
        return self._json({"logs": logs})

    async def _h_job_stop(self, request):
        from aiohttp import web

        try:
            stopped = await self._job_call("stop_job",
                                           request.match_info["job_id"])
        except Exception as e:
            return web.json_response({"error": str(e)}, status=404)
        return self._json({"stopped": bool(stopped)})

    async def _h_summary(self, request):
        nodes = await self._gcs("get_nodes")
        actors = await self._gcs("list_actors")
        total: dict = {}
        for n in nodes:
            if not n.alive:
                continue
            for k, v in n.resources_total.quantities.items():
                total[k] = total.get(k, 0) + v
        return self._json({
            "time": time.time(),
            "nodes_alive": sum(1 for n in nodes if n.alive),
            "nodes_dead": sum(1 for n in nodes if not n.alive),
            "total_resources": total,
            "actors_total": len(actors),
            "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        })

    async def _h_node_stats(self, request):
        """Aggregated from the per-node agents' pushes (GCS KV
        ns=node_stats) — ONE KV scan regardless of cluster size, instead
        of a live RPC fan-out to every nodelet (ref: reporter agents
        pushing to the head). `?live=1` forces the old direct fan-out for
        debugging a wedged agent."""
        if request.query.get("live") == "1":
            nodes = [n for n in await self._gcs("get_nodes") if n.alive]

            async def one(n):
                try:
                    return await self.pool.get(tuple(n.nodelet_addr)).call(
                        "node_stats", timeout=5.0)
                except Exception as e:  # noqa: BLE001 — best effort
                    return {"error": str(e)}

            stats = await asyncio.gather(*(one(n) for n in nodes))
            return self._json({n.node_id.hex(): st
                               for n, st in zip(nodes, stats)})
        try:
            out = await self._scan_node_stats()
        except Exception as e:   # noqa: BLE001
            out = {"error": str(e)}
        return self._json(out)

    async def _scan_node_stats(self) -> dict:
        """node_id hex -> last agent sample, concurrent kv_gets (one
        round-trip wave, not N serial), dead nodes filtered out."""
        alive = {n.node_id.binary()
                 for n in await self._gcs("get_nodes") if n.alive}
        keys = [k for k in await self._gcs("kv_keys", ns="node_stats")
                if k in alive]
        raws = await asyncio.gather(
            *(self._gcs("kv_get", ns="node_stats", key=k) for k in keys))
        return {k.hex(): json.loads(raw)
                for k, raw in zip(keys, raws) if raw}

    async def _h_metrics(self, request):
        """Prometheus exposition (ref: dashboard/modules/metrics/ +
        metrics_agent.py exposition)."""
        from aiohttp import web

        from ray_tpu.util.metrics import render_prometheus

        lines = []
        try:
            keys = await self._gcs("kv_keys", ns="metrics")
            raws = await asyncio.gather(
                *(self._gcs("kv_get", ns="metrics", key=k) for k in keys))
            for key, raw in zip(keys, raws):
                if raw is None:
                    continue
                lines.extend(render_prometheus(key.decode(), json.loads(raw)))
        except Exception as e:  # noqa: BLE001
            lines.append(f"# metrics collection error: {e}")
        try:
            lines.extend(await self._system_series())
        except Exception as e:  # noqa: BLE001
            lines.append(f"# system series error: {e}")
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def _system_series(self) -> list:
        """System metrics derived from the per-node agent pushes + GCS
        state (ref: metric_defs.h system gauges flowing through the
        metrics agent). These are the series the generated Grafana
        dashboard (dashboard/grafana.py) graphs."""
        out = []

        def g(name, help_, pairs):
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} gauge")
            for tags, v in pairs:
                label = ",".join(f'{k}="{v2}"' for k, v2 in
                                 sorted(tags.items()))
                out.append(f"{name}{{{label}}} {v}" if label
                           else f"{name} {v}")

        stats = {nid[:12]: s
                 for nid, s in (await self._scan_node_stats()).items()}
        g("raytpu_object_store_bytes_in_use", "shm store bytes per node",
          [({"node": n}, s.get("store_bytes", 0))
           for n, s in stats.items()])
        g("raytpu_object_store_num_objects", "store objects per node",
          [({"node": n}, s.get("store_objects", 0))
           for n, s in stats.items()])
        g("raytpu_spilled_bytes_total", "bytes spilled per node",
          [({"node": n}, s.get("spilled_bytes", 0))
           for n, s in stats.items()])
        g("raytpu_workers_alive", "workers per node",
          [({"node": n}, len(s.get("workers", {})))
           for n, s in stats.items()])
        g("raytpu_pending_leases", "queued lease requests per node",
          [({"node": n}, s.get("pending_leases", 0))
           for n, s in stats.items()])
        g("raytpu_oom_kills_total", "OOM kills per node",
          [({"node": n}, s.get("oom_kills", 0)) for n, s in stats.items()])
        g("raytpu_node_load_1m", "host 1m load per node",
          [({"node": n}, s.get("host", {}).get("load_1m", 0))
           for n, s in stats.items()])
        g("raytpu_node_mem_available_bytes", "host available memory",
          [({"node": n}, s.get("host", {}).get("mem_available", 0))
           for n, s in stats.items()])
        actors = await self._gcs("list_actors")
        g("raytpu_actors_alive", "actors in ALIVE state",
          [({}, sum(1 for a in actors if a["state"] == "ALIVE"))])
        nodes = await self._gcs("get_nodes")
        g("raytpu_nodes_alive", "cluster nodes alive",
          [({}, sum(1 for n in nodes if n.alive))])
        return out

    async def _h_logs(self, request):
        """List/serve session log files (ref: dashboard log module)."""
        from aiohttp import web

        logs_dir = os.path.join(self.session_dir, "logs")
        name = request.query.get("file")
        if not os.path.isdir(logs_dir):
            return self._json([])
        if name is None:
            return self._json(sorted(os.listdir(logs_dir)))
        path = os.path.realpath(os.path.join(logs_dir, name))
        root = os.path.realpath(logs_dir)
        if os.path.commonpath([path, root]) != root \
                or not os.path.isfile(path):
            return web.Response(status=404, text="no such log")
        tail = int(request.query.get("tail", 1000))
        with open(path, "r", errors="replace") as f:
            lines = f.readlines()[-tail:]
        return web.Response(text="".join(lines), content_type="text/plain")

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> Address:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._h_index)
        app.router.add_get("/api/v0/nodes", self._h_nodes)
        app.router.add_get("/api/v0/actors", self._h_actors)
        app.router.add_get("/api/v0/tasks", self._h_tasks)
        app.router.add_get("/api/v0/task_summary", self._h_task_summary)
        app.router.add_get("/api/v0/jobs", self._h_jobs)
        app.router.add_post("/api/jobs/", self._h_job_submit)
        app.router.add_get("/api/jobs/", self._h_job_list)
        app.router.add_get("/api/jobs/{job_id}", self._h_job_info)
        app.router.add_get("/api/jobs/{job_id}/logs", self._h_job_logs)
        app.router.add_post("/api/jobs/{job_id}/stop", self._h_job_stop)
        app.router.add_get("/api/v0/summary", self._h_summary)
        app.router.add_get("/api/v0/node_stats", self._h_node_stats)
        app.router.add_get("/api/v0/edge_stats", self._h_edge_stats)
        app.router.add_get("/api/v0/health", self._h_health)
        app.router.add_get("/api/v0/memory", self._h_memory)
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_get("/api/v0/logs", self._h_logs)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        # resolve ephemeral port
        for sock in self._site._server.sockets:  # noqa: SLF001
            self.port = sock.getsockname()[1]
            break
        return (self.host, self.port)

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()


def start_dashboard(gcs_addr: Address, session_dir: str = "",
                    host: str = "127.0.0.1", port: int = 8265,
                    loop: Optional[asyncio.AbstractEventLoop] = None
                    ) -> "DashboardHead":
    """Start a dashboard on an existing asyncio loop (or a fresh thread).

    Blocks until the server is bound (so `head.port` is resolved even for
    port=0) and re-raises any startup failure in the caller."""
    head = DashboardHead(gcs_addr, session_dir, host, port)
    if loop is not None:
        fut = asyncio.run_coroutine_threadsafe(head.start(), loop)
        fut.result(timeout=10)
        return head
    import threading

    started = threading.Event()
    failure: list = []

    def _run():
        lp = asyncio.new_event_loop()
        asyncio.set_event_loop(lp)
        try:
            lp.run_until_complete(head.start())
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            failure.append(e)
            started.set()
            return
        started.set()
        lp.run_forever()

    t = threading.Thread(target=_run, daemon=True, name="raytpu-dashboard")
    t.start()
    if not started.wait(10):
        raise TimeoutError("dashboard did not start within 10s")
    if failure:
        raise failure[0]
    return head


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--gcs-address", required=True, help="host:port")
    ap.add_argument("--session-dir", default="")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8265)
    args = ap.parse_args()
    host, port = args.gcs_address.rsplit(":", 1)

    async def _serve():
        head = DashboardHead((host, int(port)), args.session_dir, args.host,
                             args.port)
        addr = await head.start()
        print(json.dumps({"dashboard_url": f"http://{addr[0]}:{addr[1]}"}),
              flush=True)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_serve())


if __name__ == "__main__":
    main()
