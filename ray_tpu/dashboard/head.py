"""Dashboard head server.

Reference: dashboard/head.py — an aiohttp server on the head node serving
pluggable modules (dashboard/utils.py:40 DashboardHeadModule); we fold the
state/metrics/jobs/logs modules into route groups on one app. Talks to the
GCS directly over the RPC layer (no driver Runtime required), like the
reference head's GcsClient.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any, Optional, Tuple

from ray_tpu.core.rpc import ClientPool

Address = Tuple[str, int]


def _jsonable(v: Any):
    """Best-effort conversion of dataclasses / ids / bytes for JSON."""
    if isinstance(v, dict):
        return {_jsonable_key(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, bytes):
        return v.hex()
    if hasattr(v, "hex") and not isinstance(v, (int, float)):
        try:
            return v.hex()
        except TypeError:
            pass
    if hasattr(v, "__dataclass_fields__"):
        return {f: _jsonable(getattr(v, f)) for f in v.__dataclass_fields__}
    if hasattr(v, "quantities"):
        return _jsonable(v.quantities)
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def _jsonable_key(k: Any):
    if isinstance(k, bytes):
        return k.hex()
    if isinstance(k, (str, int, float, bool)):
        return k
    return str(k)


_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title></head>
<body style="font-family: monospace">
<h2>ray_tpu dashboard</h2>
<ul>
<li><a href="/api/v0/summary">cluster summary</a></li>
<li><a href="/api/v0/nodes">nodes</a></li>
<li><a href="/api/v0/actors">actors</a></li>
<li><a href="/api/v0/tasks">task events</a></li>
<li><a href="/api/v0/jobs">jobs</a></li>
<li><a href="/api/v0/node_stats">per-node stats</a></li>
<li><a href="/metrics">prometheus metrics</a></li>
<li><a href="/api/v0/logs">log files</a></li>
</ul>
</body></html>"""


class DashboardHead:
    def __init__(self, gcs_addr: Address, session_dir: str = "",
                 host: str = "127.0.0.1", port: int = 8265):
        self.gcs_addr = tuple(gcs_addr)
        self.session_dir = session_dir
        self.host = host
        self.port = port
        self.pool = ClientPool()
        self._runner = None
        self._site = None

    async def _gcs(self, method: str, **kw):
        return await self.pool.get(self.gcs_addr).call(method, timeout=10.0, **kw)

    # ------------------------------------------------------------- handlers

    async def _h_index(self, request):
        from aiohttp import web

        return web.Response(text=_INDEX_HTML, content_type="text/html")

    def _json(self, payload):
        from aiohttp import web

        return web.json_response(_jsonable(payload))

    async def _h_nodes(self, request):
        nodes = await self._gcs("get_nodes")
        return self._json([{
            "node_id": n.node_id.hex(), "alive": n.alive,
            "address": list(n.nodelet_addr),
            "resources": n.resources_total.quantities,
            "labels": n.labels, "store_name": n.store_name,
        } for n in nodes])

    async def _h_actors(self, request):
        return self._json(await self._gcs("list_actors"))

    async def _h_tasks(self, request):
        limit = int(request.query.get("limit", 1000))
        return self._json(await self._gcs("list_task_events", limit=limit))

    async def _h_jobs(self, request):
        return self._json(await self._gcs("list_jobs"))

    async def _h_summary(self, request):
        nodes = await self._gcs("get_nodes")
        actors = await self._gcs("list_actors")
        total: dict = {}
        for n in nodes:
            if not n.alive:
                continue
            for k, v in n.resources_total.quantities.items():
                total[k] = total.get(k, 0) + v
        return self._json({
            "time": time.time(),
            "nodes_alive": sum(1 for n in nodes if n.alive),
            "nodes_dead": sum(1 for n in nodes if not n.alive),
            "total_resources": total,
            "actors_total": len(actors),
            "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        })

    async def _h_node_stats(self, request):
        nodes = [n for n in await self._gcs("get_nodes") if n.alive]

        async def one(n):
            try:
                return await self.pool.get(tuple(n.nodelet_addr)).call(
                    "node_stats", timeout=5.0)
            except Exception as e:  # noqa: BLE001 — per-node best effort
                return {"error": str(e)}

        stats = await asyncio.gather(*(one(n) for n in nodes))
        return self._json({n.node_id.hex(): st
                           for n, st in zip(nodes, stats)})

    async def _h_metrics(self, request):
        """Prometheus exposition (ref: dashboard/modules/metrics/ +
        metrics_agent.py exposition)."""
        from aiohttp import web

        from ray_tpu.util.metrics import render_prometheus

        lines = []
        try:
            keys = await self._gcs("kv_keys", ns="metrics")
            for key in keys:
                raw = await self._gcs("kv_get", ns="metrics", key=key)
                if raw is None:
                    continue
                lines.extend(render_prometheus(key.decode(), json.loads(raw)))
        except Exception as e:  # noqa: BLE001
            lines.append(f"# metrics collection error: {e}")
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def _h_logs(self, request):
        """List/serve session log files (ref: dashboard log module)."""
        from aiohttp import web

        logs_dir = os.path.join(self.session_dir, "logs")
        name = request.query.get("file")
        if not os.path.isdir(logs_dir):
            return self._json([])
        if name is None:
            return self._json(sorted(os.listdir(logs_dir)))
        path = os.path.realpath(os.path.join(logs_dir, name))
        root = os.path.realpath(logs_dir)
        if os.path.commonpath([path, root]) != root \
                or not os.path.isfile(path):
            return web.Response(status=404, text="no such log")
        tail = int(request.query.get("tail", 1000))
        with open(path, "r", errors="replace") as f:
            lines = f.readlines()[-tail:]
        return web.Response(text="".join(lines), content_type="text/plain")

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> Address:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._h_index)
        app.router.add_get("/api/v0/nodes", self._h_nodes)
        app.router.add_get("/api/v0/actors", self._h_actors)
        app.router.add_get("/api/v0/tasks", self._h_tasks)
        app.router.add_get("/api/v0/jobs", self._h_jobs)
        app.router.add_get("/api/v0/summary", self._h_summary)
        app.router.add_get("/api/v0/node_stats", self._h_node_stats)
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_get("/api/v0/logs", self._h_logs)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        # resolve ephemeral port
        for sock in self._site._server.sockets:  # noqa: SLF001
            self.port = sock.getsockname()[1]
            break
        return (self.host, self.port)

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()


def start_dashboard(gcs_addr: Address, session_dir: str = "",
                    host: str = "127.0.0.1", port: int = 8265,
                    loop: Optional[asyncio.AbstractEventLoop] = None
                    ) -> "DashboardHead":
    """Start a dashboard on an existing asyncio loop (or a fresh thread).

    Blocks until the server is bound (so `head.port` is resolved even for
    port=0) and re-raises any startup failure in the caller."""
    head = DashboardHead(gcs_addr, session_dir, host, port)
    if loop is not None:
        fut = asyncio.run_coroutine_threadsafe(head.start(), loop)
        fut.result(timeout=10)
        return head
    import threading

    started = threading.Event()
    failure: list = []

    def _run():
        lp = asyncio.new_event_loop()
        asyncio.set_event_loop(lp)
        try:
            lp.run_until_complete(head.start())
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            failure.append(e)
            started.set()
            return
        started.set()
        lp.run_forever()

    t = threading.Thread(target=_run, daemon=True, name="raytpu-dashboard")
    t.start()
    if not started.wait(10):
        raise TimeoutError("dashboard did not start within 10s")
    if failure:
        raise failure[0]
    return head


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--gcs-address", required=True, help="host:port")
    ap.add_argument("--session-dir", default="")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8265)
    args = ap.parse_args()
    host, port = args.gcs_address.rsplit(":", 1)

    async def _serve():
        head = DashboardHead((host, int(port)), args.session_dir, args.host,
                             args.port)
        addr = await head.start()
        print(json.dumps({"dashboard_url": f"http://{addr[0]}:{addr[1]}"}),
              flush=True)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_serve())


if __name__ == "__main__":
    main()
