"""Dashboard: HTTP head server exposing cluster state, metrics, and logs.

Reference: dashboard/head.py (aiohttp head server) + dashboard/modules/
(state, metrics, jobs, logs). The React client is out of scope; every view
is JSON (the reference's dashboard modules are JSON APIs under the UI too),
plus a Prometheus /metrics endpoint and a minimal HTML overview.
"""

from ray_tpu.dashboard.head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
