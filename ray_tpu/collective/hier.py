"""Hierarchical backend: two-level topology-aware allreduce (``"hier"``).

The standard host-collective scaling fix (Horovod's hierarchical
allreduce, Sergeev & Del Balso 2018) mapped onto this framework's
bandwidth domains (topology.py): ranks that share a node exchange over
the shm object store (cheap), and only one **leader per node** speaks on
the inter-node ring (expensive). Allreduce:

    1. intra-node reduce   — members push payloads to their node leader,
                             which accumulates in ascending-rank order;
    2. inter-node ring     — leaders ring-allreduce the node sums
                             (bandwidth-optimal across the slow domain);
    3. intra-node broadcast — leaders fan the result back out.

Inter-node traffic per node is 2·(L−1)/L of the payload (L = number of
nodes) regardless of how many ranks each node packs — the win over flat
ring grows with ranks-per-node. On a single node this degenerates to a
leader-funnel, which the equivalence suite still exercises as a distinct
code path.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ray_tpu.collective.group import GroupContext
from ray_tpu.collective.ring import (ring_allreduce_flat, ring_allgather_obj,
                                     tree_barrier, tree_broadcast)


class HierBackend:
    name = "hier"

    def __init__(self, ctx: GroupContext, pipeline_chunks: int = 4):
        self.ctx = ctx
        self.pipeline_chunks = pipeline_chunks
        self.topo = ctx.topology
        self._all = list(range(ctx.world))

    def _intra_reduce(self, buf: np.ndarray, tag: str) -> np.ndarray:
        """Members → leader; leader returns the node-local sum."""
        ctx = self.ctx
        leader = self.topo.leader_of(ctx.rank)
        if ctx.rank != leader:
            ctx.send(leader, f"{tag}:ir:{ctx.rank}", buf)
            return buf
        # ascending-rank accumulation keeps the reduction order
        # deterministic and identical to the gather backend's
        total = None
        for r in self.topo.peers_on_node(ctx.rank):
            part = buf if r == ctx.rank else np.asarray(
                ctx.recv(r, f"{tag}:ir:{r}", op="allreduce"))
            total = part if total is None else total + part
        return total

    def _intra_broadcast(self, value, tag: str):
        ctx = self.ctx
        leader = self.topo.leader_of(ctx.rank)
        if ctx.rank == leader:
            for r in self.topo.peers_on_node(ctx.rank):
                if r != ctx.rank:
                    ctx.send(r, f"{tag}:ib:{r}", value)
            return value
        return ctx.recv(leader, f"{tag}:ib:{ctx.rank}", op="allreduce")

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        ctx = self.ctx
        arr = np.asarray(arr)
        seq = ctx.next_seq()
        tag = f"{seq}:h"
        buf = np.ascontiguousarray(arr).ravel().copy()
        total = self._intra_reduce(buf, tag)
        if self.topo.is_leader(ctx.rank):
            leaders = list(self.topo.leader_ranks())
            ring_allreduce_flat(ctx, total, leaders, f"{tag}:lr",
                                self.pipeline_chunks)
        out = np.asarray(self._intra_broadcast(
            total if self.topo.is_leader(ctx.rank) else None, tag))
        return out.reshape(arr.shape)

    def allgather(self, value) -> List[Any]:
        ctx = self.ctx
        seq = ctx.next_seq()
        tag = f"{seq}:hg"
        leader = self.topo.leader_of(ctx.rank)
        if ctx.rank != leader:
            ctx.send(leader, f"{tag}:ir:{ctx.rank}", value)
        else:
            node_vals = {}
            for r in self.topo.peers_on_node(ctx.rank):
                node_vals[r] = value if r == ctx.rank else ctx.recv(
                    r, f"{tag}:ir:{r}", op="allgather")
            leaders = list(self.topo.leader_ranks())
            merged: dict = {}
            for vals in ring_allgather_obj(ctx, node_vals, leaders,
                                           f"{tag}:lg").values():
                merged.update(vals)
        full = self._intra_broadcast(
            merged if ctx.rank == leader else None, tag)
        return [full[r] for r in range(ctx.world)]

    def broadcast(self, value, src_rank: int):
        seq = self.ctx.next_seq()
        return tree_broadcast(self.ctx, value, src_rank, self._all,
                              f"{seq}:hb")

    def reducescatter(self, arr: np.ndarray) -> np.ndarray:
        # full hierarchical reduce, then keep this rank's axis-0 block —
        # trades some intra-node broadcast bytes for reusing the
        # leader-ring path (inter-node volume is what hier optimizes)
        arr = np.ascontiguousarray(arr)
        world = self.ctx.world
        total = self.allreduce(arr)
        per = arr.shape[0] // world
        return total[self.ctx.rank * per:(self.ctx.rank + 1) * per]

    def barrier(self) -> None:
        seq = self.ctx.next_seq()
        tree_barrier(self.ctx, self._all, f"{seq}:hbar")
