"""Minimal pytree pack/unpack for collective payloads.

Collectives operate on flat numpy buffers; users hold nested containers
(gradient trees, metric dicts). This flattens nested dict/list/tuple
structures of array-likes into per-dtype contiguous buffers — one
collective round per dtype group instead of one per leaf — and restores
the original structure afterwards. Deliberately jax-free: host
collectives must not pull jax into CPU-only rollout workers (see
rl/core.py CPU_WORKER_ENV).

Packing order is structure-deterministic (dict keys sorted), so every
rank packs identically and cross-backend results stay comparable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


def tree_flatten(tree) -> Tuple[List[np.ndarray], Any]:
    """Flatten nested dict/list/tuple into (leaves, treedef)."""
    leaves: List[np.ndarray] = []

    def rec(node):
        if isinstance(node, dict):
            keys = sorted(node)
            return ("d", keys, [rec(node[k]) for k in keys])
        if isinstance(node, (list, tuple)):
            tag = "l" if isinstance(node, list) else "t"
            return (tag, None, [rec(x) for x in node])
        leaves.append(np.asarray(node))
        return ("*", None, None)

    treedef = rec(tree)
    return leaves, treedef


def tree_unflatten(treedef, leaves: List[np.ndarray]):
    it = iter(leaves)

    def rec(node):
        tag, keys, children = node
        if tag == "d":
            return {k: rec(c) for k, c in zip(keys, children)}
        if tag == "l":
            return [rec(c) for c in children]
        if tag == "t":
            return tuple(rec(c) for c in children)
        return next(it)

    return rec(treedef)


def is_leaf(value) -> bool:
    return not isinstance(value, (dict, list, tuple))


def pack_leaves(leaves: List[np.ndarray]):
    """Group leaves by dtype and concatenate raveled data.

    Returns (buffers, layout): buffers is a list of 1-D arrays (one per
    dtype group, iterated in first-appearance order); layout records per
    leaf (group index, offset, size, shape) for unpacking.
    """
    group_order: List[str] = []
    groups: Dict[str, List[np.ndarray]] = {}
    layout = []
    offsets: Dict[str, int] = {}
    for leaf in leaves:
        arr = np.asarray(leaf)
        key = arr.dtype.str
        if key not in groups:
            groups[key] = []
            offsets[key] = 0
            group_order.append(key)
        gi = group_order.index(key)
        layout.append((gi, offsets[key], arr.size, arr.shape))
        groups[key].append(arr.ravel())
        offsets[key] += arr.size
    buffers = [np.concatenate(groups[k]) if groups[k]
               else np.empty((0,)) for k in group_order]
    return buffers, layout


def unpack_leaves(buffers, layout) -> List[np.ndarray]:
    out = []
    for gi, off, size, shape in layout:
        out.append(np.asarray(buffers[gi][off:off + size]).reshape(shape))
    return out
