"""The legacy coordinator-funnel backend (``"gather"``).

Every rank ships its full payload to one coordinator actor which
combines and re-broadcasts — O(world × bytes) through a single Python
process. Still the right tool for small payloads (one RTT, no
per-round peer bookkeeping) and the compatibility baseline the
equivalence suite measures ring/hier against; the coordinator actor
additionally serves as the group's bootstrap rendezvous (group.py).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ray_tpu.collective.group import GroupContext


class GatherBackend:
    name = "gather"

    def __init__(self, ctx: GroupContext):
        self.ctx = ctx

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        return np.asarray(self.ctx.coord_exchange("allreduce_sum", arr))

    def allgather(self, value) -> List[Any]:
        return self.ctx.coord_exchange("allgather", value)

    def broadcast(self, value, src_rank: int):
        data = value if self.ctx.rank == src_rank else None
        return self.ctx.coord_exchange("broadcast", data)

    def reducescatter(self, arr: np.ndarray) -> np.ndarray:
        return np.asarray(self.ctx.coord_exchange("reducescatter", arr))

    def barrier(self) -> None:
        self.ctx.coord_exchange("barrier", None)
