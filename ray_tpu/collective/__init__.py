"""ray_tpu.collective — topology-aware host collectives.

Pluggable algorithms for exchanging CPU-side payloads between actors
(rollout fleets, data-pipeline shuffles, cross-slice host exchanges):

- ``gather`` — legacy single-coordinator funnel (small payloads);
- ``ring``   — chunked, pipelined ring reduce-scatter/all-gather
  (bandwidth-optimal: 2·(N−1)/N of the payload per rank);
- ``hier``   — hierarchical two-level allreduce (intra-node reduce →
  leader ring → intra-node broadcast), topology-aware via GCS node ids;
- ``auto``   — selected per call from world size and payload bytes.

Device collectives (psum/all-gather over ICI) stay inside jitted
programs — see ray_tpu.parallel and ARCHITECTURE.md "Host collectives".

    from ray_tpu import collective as col

    col.init_collective_group(world_size, rank, "fleet", backend="auto")
    total = col.allreduce(grads_pytree, "fleet")        # sync
    fut = col.allreduce_async(next_grads, "fleet")      # overlap compute
    col.destroy_collective_group("fleet")

Failure semantics: per-round timeouts + peer liveness probing — a dead
rank surfaces as ``CollectiveError`` on every survivor instead of a
deadlock.
"""

from ray_tpu.collective.api import (GroupClient, allgather, allgather_async,
                                    allreduce, allreduce_async, barrier,
                                    barrier_async, broadcast, broadcast_async,
                                    coordinator_stats,
                                    destroy_collective_group,
                                    generation_name,
                                    get_collective_group_size,
                                    get_group_topology, get_rank, group_stats,
                                    init_collective_group, reducescatter,
                                    reducescatter_async,
                                    reform_collective_group,
                                    reset_transfer_stats,
                                    transfer_stats)
from ray_tpu.collective.errors import CollectiveError, CollectiveTimeoutError
from ray_tpu.collective.registry import (available_backends,
                                         register_backend, select_backend)
from ray_tpu.collective.topology import Topology

__all__ = [
    "init_collective_group", "destroy_collective_group",
    "reform_collective_group", "generation_name",
    "allreduce", "allgather", "broadcast", "reducescatter", "barrier",
    "allreduce_async", "allgather_async", "broadcast_async",
    "reducescatter_async", "barrier_async",
    "get_rank", "get_collective_group_size", "get_group_topology",
    "transfer_stats", "reset_transfer_stats", "coordinator_stats",
    "group_stats",
    "available_backends", "register_backend", "select_backend",
    "CollectiveError", "CollectiveTimeoutError", "Topology", "GroupClient",
]
