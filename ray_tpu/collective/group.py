"""Group bootstrap + the peer-to-peer transport every backend rides.

Two kinds of helper actors per group:

- one ``_Coordinator`` (created by rank 0, named ``_collective_{group}``)
  — the legacy gather/broadcast rendezvous. It doubles as the bootstrap
  barrier: every rank allgathers its (node id, mailbox handle) through
  it once, which yields the membership table the ``Topology`` and the
  peer-to-peer backends are built from.
- one ``_Mailbox`` per rank (named ``_collective_{group}_mbx{rank}``) —
  a keyed async slot store. Ring/hierarchical backends move chunks by
  pushing into the *receiver's* mailbox (object-store peer-to-peer:
  sender worker → receiver-mailbox worker, no global fan-in point) and
  the receiver draining its own mailbox. Every ``take`` carries a
  server-side timeout so a dead sender can never park a round forever.

Failure detection: a timed-out ``take``/``exchange`` returns a sentinel
instead of blocking; the client then pings every peer mailbox and raises
``CollectiveTimeoutError`` naming the unresponsive ranks.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu.collective.errors import CollectiveError, CollectiveTimeoutError
from ray_tpu.collective.topology import Topology
from ray_tpu.observability import health as _health
from ray_tpu.observability import memory as _memory
from ray_tpu.observability.edges import record_transfer

#: Sentinel dict key marking a server-side timeout reply.
TIMEOUT_KEY = "__col_timeout__"
#: Sentinel dict key marking a zero-copy envelope: the mailbox carries
#: only {ZC_KEY: True, "ref": ObjectRef, "nbytes": n}; the bulk bytes sit
#: in the object store and the receiver resolves them via the pinned
#: zero-copy local read (core/runtime.py _ReadPin).
ZC_KEY = "__col_zc_ref__"
#: Receiver → sender ack keys (sender frees its pinned chunk copy on ack).
ACK_PREFIX = "__ack__:"
#: Sender-side cap on unacked zero-copy bytes before send() blocks on a
#: bounded ack reap — bounds store usage for a peer that drains slowly.
ZC_WINDOW_BYTES = 64 * 1024 * 1024


def _is_timeout(v) -> bool:
    return isinstance(v, dict) and TIMEOUT_KEY in v


def _is_zc(v) -> bool:
    return isinstance(v, dict) and ZC_KEY in v


# --------------------------------------------------------------------------
# helper actors
# --------------------------------------------------------------------------


@ray_tpu.remote
class _Mailbox:
    """Keyed rendezvous slots for one rank's inbound collective traffic.

    Methods are deliberately SYNCHRONOUS: with max_concurrency > 1 they
    run on the worker's executor threads, where blocking is allowed —
    packaging a large return pins it in the object store via a blocking
    nodelet RPC, which the runtime forbids on the event-loop thread (an
    async ``take`` returning a big chunk would trip that guard)."""

    def __init__(self):
        import threading

        self.slots: Dict[str, Any] = {}
        self.cv = threading.Condition()

    def put(self, key: str, value) -> bool:
        with self.cv:
            self.slots[key] = value
            self.cv.notify_all()
        return True

    def put_many(self, items: Dict[str, Any]) -> bool:
        """One RPC delivers a whole wave of keyed slots (a ring step's
        pipeline_chunks sub-chunks) instead of one actor call each."""
        with self.cv:
            self.slots.update(items)
            self.cv.notify_all()
        return True

    def take(self, key: str, timeout_s: float):
        """Block until `key` arrives (or time out → sentinel), then pop it."""
        with self.cv:
            if not self.cv.wait_for(lambda: key in self.slots,
                                    timeout=timeout_s):
                return {TIMEOUT_KEY: key}
            return self.slots.pop(key)

    def drain(self, prefix: str, timeout_s: float = 0.0) -> List[str]:
        """Pop and return every key starting with `prefix` (ack reaping).
        With timeout_s > 0 blocks until at least one match (or timeout)."""
        with self.cv:
            if timeout_s > 0:
                self.cv.wait_for(
                    lambda: any(k.startswith(prefix) for k in self.slots),
                    timeout=timeout_s)
            keys = [k for k in self.slots if k.startswith(prefix)]
            for k in keys:
                del self.slots[k]
            return keys

    def ping(self) -> bool:
        return True


@ray_tpu.remote
class _Coordinator:
    """Gather-style rendezvous: every rank contributes, everyone gets the
    combined result (the legacy O(world × bytes) funnel — kept as the
    ``gather`` backend and as the bootstrap allgather)."""

    def __init__(self, world_size: int):
        import threading

        self.world = world_size
        self.rounds: Dict[tuple, dict] = {}
        self.cv = threading.Condition()
        self.bytes_in = 0          # transfer accounting: fan-in volume

    def exchange(self, op: str, seq: int, rank: int, data,
                 timeout_s: float = 300.0):
        """All ranks call with their contribution; returns the combined
        result once everyone arrived, or a timeout sentinel naming the
        ranks that never showed up. Synchronous on purpose — see _Mailbox
        (large combined results must be packaged off the event loop)."""
        key = (op, seq)
        # payload_nbytes, not ndarray-only: gather's fan-in volume must
        # stay honest for lists/dicts/pytrees too (bench + tests assert it)
        self.bytes_in += payload_nbytes(data)
        with self.cv:
            slot = self.rounds.setdefault(key, {"parts": {}, "result": None})
            slot["parts"][rank] = data
            if len(slot["parts"]) == self.world:
                slot["result"] = self._combine(op, slot["parts"])
                self.cv.notify_all()
            else:
                def done():
                    s = self.rounds.get(key)
                    return s is None or s["result"] is not None

                if not self.cv.wait_for(done, timeout=timeout_s):
                    missing = [r for r in range(self.world)
                               if r not in slot["parts"]]
                    return {TIMEOUT_KEY: missing}
            result = self.rounds[key]["result"][rank]
            slot["parts"].pop(rank, None)
            if not slot["parts"]:
                self.rounds.pop(key, None)
            return result

    def _combine(self, op: str, parts_by_rank: Dict[int, Any]) -> list:
        parts = [parts_by_rank[r] for r in range(self.world)]
        if op == "allreduce_sum":
            out = parts[0]
            for p in parts[1:]:
                out = out + p
            return [out] * self.world
        if op == "allgather":
            return [list(parts)] * self.world
        if op == "barrier":
            return [True] * self.world
        if op == "broadcast":
            srcs = [p for p in parts if p is not None]
            if not srcs:
                # every rank passed None: a bare StopIteration here would
                # vanish inside the async handler — name the misuse
                raise ValueError(
                    "broadcast: no source rank provided data")
            return [srcs[0]] * self.world
        if op == "reducescatter":
            total = parts[0]
            for p in parts[1:]:
                total = total + p
            return list(np.array_split(total, self.world))
        raise ValueError(op)

    def stats(self) -> dict:
        return {"bytes_in": self.bytes_in}

    def ping(self) -> bool:
        return True


# --------------------------------------------------------------------------
# transfer accounting
# --------------------------------------------------------------------------


#: One priced exemplar per unknown type — pickling EVERY send's payload
#: to size it was a per-call hot spot; sizes within a type are close
#: enough for accounting, and the cache is bounded.
_FALLBACK_NBYTES: Dict[type, int] = {}
_FALLBACK_NBYTES_MAX = 256


def payload_nbytes(obj) -> int:
    """Approximate wire size of a collective payload.

    Fast paths cover everything the transport actually moves (ndarray,
    bytes, zero-copy envelopes, containers of those); arbitrary objects
    are priced by pickling one exemplar per type (bounded cache) instead
    of pickling on every send."""
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if isinstance(obj, memoryview):
        return int(obj.nbytes)
    if obj is None or isinstance(obj, (bool, int, float)):
        return 8
    if isinstance(obj, str):
        return 8 + len(obj)
    if isinstance(obj, dict):
        if ZC_KEY in obj:
            # zero-copy envelope: the wire carries a tiny ref, but the
            # TRANSFER is the chunk it names — account the chunk
            try:
                return int(obj["nbytes"])
            except (KeyError, TypeError, ValueError):
                pass
        return sum(payload_nbytes(o) for o in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(payload_nbytes(o) for o in obj)
    t = type(obj)
    n = _FALLBACK_NBYTES.get(t)
    if n is None:
        try:
            n = len(pickle.dumps(obj, protocol=5))
        except Exception:
            n = 64
        if len(_FALLBACK_NBYTES) < _FALLBACK_NBYTES_MAX:
            _FALLBACK_NBYTES[t] = n
    return n


class TransferStats:
    """Per-rank byte accounting — the hook the bandwidth-optimality tests
    and ``bench.py --bench collective`` assert against."""

    def __init__(self):
        self.bytes_sent = 0          # total payload bytes this rank pushed
        self.bytes_sent_inter = 0    # subset that crossed a node boundary
        self.bytes_recv = 0
        self.sends = 0
        self.recvs = 0
        self.zc_sends = 0            # sends that rode the zero-copy tier
        self.zc_bytes_sent = 0       # ...and their payload bytes
        self.eager_sends = 0         # sends that rode the inline mailbox
        self.coord_sends = 0         # coordinator exchanges (gather/boot)

    def snapshot(self) -> dict:
        return {"bytes_sent": self.bytes_sent,
                "bytes_sent_inter": self.bytes_sent_inter,
                "bytes_recv": self.bytes_recv,
                "sends": self.sends, "recvs": self.recvs,
                "zc_sends": self.zc_sends,
                "zc_bytes_sent": self.zc_bytes_sent,
                "eager_sends": self.eager_sends,
                "coord_sends": self.coord_sends}

    def reset(self):
        self.__init__()


# --------------------------------------------------------------------------
# group context
# --------------------------------------------------------------------------


def _actor_name(group: str, suffix: str = "") -> str:
    return f"_collective_{group}{suffix}"


def _current_config():
    """The live runtime's Config (workers inherit init()'s system config
    via the nodelet spawn), or the env-layer GLOBAL_CONFIG outside one."""
    from ray_tpu.core import runtime as rt

    r = rt.current_runtime_or_none()
    if r is not None and getattr(r, "cfg", None) is not None:
        return r.cfg
    from ray_tpu.core.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG


def _resolve_named(name: str, deadline_s: float = 30.0):
    deadline = time.time() + deadline_s
    while True:
        try:
            return ray_tpu.get_actor(name)
        except ValueError:
            if time.time() > deadline:
                raise
            time.sleep(0.1)


class GroupContext:
    """One rank's view of a collective group: membership, topology,
    mailbox handles, sequencing, transfer accounting.

    Ops must be issued in the same order on every rank (standard
    collective contract); ``seq`` ties the rounds together.
    """

    #: transport → (eager_threshold, zerocopy_threshold) overrides; None
    #: means "take it from Config". zerocopy_threshold None disables the
    #: zero-copy tier entirely; eager 1<<62 forces everything inline.
    TRANSPORTS = ("auto", "mailbox", "zerocopy", "eager")

    def __init__(self, name: str, world_size: int, rank: int,
                 timeout_s: float = 60.0, transport: str = "auto"):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} outside world of {world_size}")
        if transport not in self.TRANSPORTS:
            raise ValueError(f"unknown collective transport {transport!r}; "
                             f"one of {self.TRANSPORTS}")
        self.name = name
        self.world = world_size
        self.rank = rank
        self.timeout_s = float(timeout_s)
        self.seq = 0
        self.stats = TransferStats()
        self.mailboxes: Dict[int, Any] = {}
        self.transport = transport
        cfg = _current_config()
        if transport == "mailbox":        # the pre-zero-copy transport
            self.eager_threshold, self.zc_threshold = 0, None
        elif transport == "eager":        # everything one inline message
            self.eager_threshold, self.zc_threshold = 1 << 62, None
        elif transport == "zerocopy":     # every ndarray/bytes chunk via ref
            self.eager_threshold, self.zc_threshold = 0, 1
        else:
            self.eager_threshold = int(cfg.collective_eager_threshold_bytes)
            zc = int(cfg.collective_zerocopy_threshold_bytes)
            self.zc_threshold = zc if zc > 0 else None
        #: unacked zero-copy chunks this rank put(): key → (ref, nbytes,
        #: waiter_rank). The ref pins the store copy until the receiver's
        #: resolve ack — explicit lifetime instead of racing the
        #: borrower handoff.
        self._zc_inflight: Dict[str, Tuple[Any, int, int]] = {}
        self._zc_bytes = 0
        # Measured coordinator-funnel model (feeds the cost-based backend
        # auto-selector): RTT EWMA from small exchanges, effective funnel
        # bandwidth from bulk ones.
        self.coord_lat_ewma: Optional[float] = None
        self.coord_bw_ewma: Optional[float] = None
        # Progress beacon for the watchdog (observability/health.py):
        # armed around every blocking wait with the op + rank it waits
        # on, so a hung round is flagged as a StallEvent naming the
        # suspect rank — typically long before timeout_s fires.
        self._beacon = _health.beacon(
            f"collective:{name}:r{rank}",
            deadline_s=float(cfg.collective_stall_deadline_s))

        coord_name = _actor_name(name)
        mbx_name = _actor_name(name, f"_mbx{rank}")
        # Own mailbox first (peers resolve it by name), then rank 0 brings
        # up the coordinator everyone bootstraps through.
        # Fractional CPU on purpose: 0 < cpu < 1 makes helper actors
        # lane-packable (nodelet._laneable) so a group's whole helper
        # fleet shares one worker process instead of each holding a
        # max_workers_per_node slot — many live groups would otherwise
        # exhaust the worker cap and wedge the next group's bootstrap.
        self.mailbox = _Mailbox.options(
            name=mbx_name, num_cpus=0.01,
            max_concurrency=max(4 * world_size, 16)).remote()
        if rank == 0:
            try:
                self.coord = _Coordinator.options(
                    name=coord_name, num_cpus=0.01,
                    max_concurrency=max(world_size * 2, 4)).remote(world_size)
            except ValueError:
                self.coord = _resolve_named(coord_name)
        else:
            self.coord = _resolve_named(coord_name)

        try:
            node_id = ray_tpu.get_runtime_context().get_node_id()
        except Exception:
            node_id = "local"
        # Bootstrap budget is deliberately generous: joining can pay for
        # several fresh worker-process spawns (~5 s of jax import each,
        # more on a loaded box) before the first rank even registers.
        table = self.coord_exchange(
            "allgather", {"rank": rank, "node": node_id,
                          "mailbox": self.mailbox},
            timeout_s=max(self.timeout_s, 120.0))
        self.mailboxes = {e["rank"]: e["mailbox"] for e in table}
        self.topology = Topology.build({e["rank"]: e["node"] for e in table})

    # -- coordinator path (gather backend + bootstrap) -------------------

    def coord_exchange(self, op: str, data, timeout_s: Optional[float] = None):
        t = self.timeout_s if timeout_s is None else timeout_s
        self.seq += 1
        n = payload_nbytes(data)
        self.stats.bytes_sent += n
        self.stats.sends += 1
        self.stats.coord_sends += 1
        t0 = time.perf_counter()
        self._beacon.arm(op=op, seq=self.seq, phase="coord",
                         waiting_on="coordinator")
        try:
            out = self._checked_get(
                self.coord.exchange.remote(op, self.seq, self.rank, data, t),
                op=op, budget_s=t)
        finally:
            self._beacon.tick()
            self._beacon.disarm()
        if _is_timeout(out):
            self._flight_dump(f"collective:{op}:coord_timeout",
                              suspect_ranks=out[TIMEOUT_KEY], seq=self.seq)
            raise CollectiveTimeoutError(
                f"collective {op} (group {self.name!r}, seq {self.seq}) "
                f"timed out after {t:.1f}s waiting for ranks {out[TIMEOUT_KEY]}",
                group_name=self.name, op=op, suspect_ranks=out[TIMEOUT_KEY])
        self._observe_coord(n, time.perf_counter() - t0)
        return out

    def _observe_coord(self, nbytes: int, dt: float) -> None:
        """Fold one funnel round into the measured coordinator model the
        cost-based auto-selector prices the gather backend with. The
        bootstrap allgather (seq 1) is excluded — it pays actor spawns,
        not transport."""
        if self.seq <= 1 or dt <= 0:
            return
        a = 0.25
        if nbytes < 4096:
            # small exchange ≈ pure rendezvous RTT (still includes rank
            # skew, which a real gather round pays too)
            self.coord_lat_ewma = (dt if self.coord_lat_ewma is None
                                   else a * dt + (1 - a) * self.coord_lat_ewma)
        elif nbytes >= 64 * 1024:
            # funnel serializes world×bytes in and out of one process;
            # invert the gather cost model for effective bandwidth
            bw = (2.0 * self.world * nbytes) / dt
            self.coord_bw_ewma = (bw if self.coord_bw_ewma is None
                                  else a * bw + (1 - a) * self.coord_bw_ewma)

    # -- peer-to-peer path (ring / hier backends) ------------------------

    def _zc_eligible(self, payload, n: int) -> bool:
        return (self.zc_threshold is not None and n >= self.zc_threshold
                and isinstance(payload, (np.ndarray, bytes, bytearray)))

    def _reap_zc_acks(self, block: bool = False) -> None:
        """Free chunks whose receivers acked their resolve. Non-blocking
        at op boundaries; when the unacked window overflows, block with a
        hard deadline (a wedged peer surfaces as ITS timeout, not as this
        rank parking forever in a reap)."""
        if not self._zc_inflight:
            return
        deadline = time.monotonic() + (min(10.0, self.timeout_s) if block
                                       else 0.0)
        while True:
            wait = min(0.25, max(0.0, deadline - time.monotonic()))
            try:
                keys = ray_tpu.get(
                    self.mailbox.drain.remote(ACK_PREFIX, wait),
                    timeout=30.0)
            except Exception:
                return               # mailbox gone: destroy() will clear
            for k in keys:
                entry = self._zc_inflight.pop(k[len(ACK_PREFIX):], None)
                if entry is not None:
                    self._zc_bytes -= entry[1]
                    _memory.tracker().unpin(entry[0].id, "await_ack")
            if (not block or self._zc_bytes <= ZC_WINDOW_BYTES
                    or time.monotonic() >= deadline):
                return

    def _tag_staged(self, ref, n: int, key: str, waiter_rank: int) -> None:
        """Attribute a staged zero-copy chunk to the collective subsystem
        and pin it with the ack it waits on — `cli blackbox` / `cli top
        mem` then name exactly which ack a stuck pinned chunk is missing
        (and which rank owes it)."""
        mem = _memory.tracker()
        mem.retag(ref.id, "collective", group=self.name, ack_key=key)
        mem.pin(ref.id, "await_ack", ack_key=key, waiter_rank=waiter_rank)

    def _stage_payload(self, key: str, payload, n: int, hops: int = 1,
                       dst_rank: int = -1):
        """Pick the wire form for one payload: zero-copy envelope (ref
        into the object store) or the inline value itself.

        `hops > 1` declares a multi-hop envelope (ring all-gather): the
        ref will be forwarded hop-to-hop and only the FINAL receiver
        acks, to this rank's mailbox under `ack_key` — forwarding is
        sequential, so the last hop resolving implies every earlier hop
        did too. The staged ref stays pinned until that single ack."""
        if not self._zc_eligible(payload, n):
            self.stats.eager_sends += 1
            return payload
        if self._zc_bytes > ZC_WINDOW_BYTES:
            self._reap_zc_acks(block=True)
        ref = ray_tpu.put(payload)
        self._zc_inflight[key] = (ref, n, dst_rank)
        self._zc_bytes += n
        self.stats.zc_sends += 1
        self.stats.zc_bytes_sent += n
        self._tag_staged(ref, n, key, dst_rank)
        return {ZC_KEY: True, "ref": ref, "nbytes": n,
                "owner": self.rank, "ack_key": key, "hops": hops}

    def send(self, dst_rank: int, key: str, payload) -> None:
        """Fire-and-forget push into dst's mailbox (object-store p2p).

        Bulk ndarray/bytes payloads at or above zc_threshold take the
        zero-copy tier: one put() into the store, only the ObjectRef
        rides the mailbox actor; the store copy stays pinned in
        _zc_inflight until the receiver acks its resolve."""
        n = payload_nbytes(payload)
        self.stats.bytes_sent += n
        self.stats.sends += 1
        if self.topology.node_of(dst_rank) != self.topology.node_of(self.rank):
            self.stats.bytes_sent_inter += n
        value = self._stage_payload(key, payload, n, dst_rank=dst_rank)
        # a lost put surfaces as the receiver's timeout + peer probe
        # raylint: disable=leaked-object-ref -- fire-and-forget by design
        self.mailboxes[dst_rank].put.remote(key, value)

    def send_many(self, dst_rank: int, items: Sequence[Tuple[str, Any]],
                  hops: int = 1) -> None:
        """send() for a wave of keyed payloads (one ring step's sub-
        chunks): the zero-copy puts batch into ONE nodelet pin RPC and
        the whole wave rides ONE mailbox put_many call. `hops` is the
        multi-hop envelope declaration (see _stage_payload)."""
        inter = (self.topology.node_of(dst_rank)
                 != self.topology.node_of(self.rank))
        entries: Dict[str, Any] = {}
        zc_wave: List[Tuple[str, Any, int]] = []
        for key, payload in items:
            n = payload_nbytes(payload)
            self.stats.bytes_sent += n
            self.stats.sends += 1
            if inter:
                self.stats.bytes_sent_inter += n
            if self._zc_eligible(payload, n):
                zc_wave.append((key, payload, n))
            else:
                self.stats.eager_sends += 1
                entries[key] = payload
        if zc_wave:
            if self._zc_bytes > ZC_WINDOW_BYTES:
                self._reap_zc_acks(block=True)
            from ray_tpu.core import runtime as rt

            r = rt.current_runtime_or_none()
            if r is not None:
                refs = r.put_batch([p for _, p, _ in zc_wave])
            else:
                refs = [ray_tpu.put(p) for _, p, _ in zc_wave]
            for (key, _, n), ref in zip(zc_wave, refs):
                self._zc_inflight[key] = (ref, n, dst_rank)
                self._zc_bytes += n
                self.stats.zc_sends += 1
                self.stats.zc_bytes_sent += n
                self._tag_staged(ref, n, key, dst_rank)
                entries[key] = {ZC_KEY: True, "ref": ref, "nbytes": n,
                                "owner": self.rank, "ack_key": key,
                                "hops": hops}
        # raylint: disable=leaked-object-ref -- fire-and-forget by design
        self.mailboxes[dst_rank].put_many.remote(entries)

    def recv(self, src_rank: int, key: str, *, op: str = ""):
        """Blocking take from OWN mailbox of the value `src_rank` pushed.

        A zero-copy envelope is resolved through the pinned local read
        (same-node: zero-copy numpy view over shm; cross-node: nodelet
        pull), then acked back to the OWNER's mailbox so it can free its
        pinned copy — the ack only fires after a successful resolve."""
        return self.recv_fwd(src_rank, key, op=op)[0]

    def forward(self, dst_rank: int, key: str, env: dict) -> None:
        """Relay a still-live zero-copy envelope to the next hop without
        re-staging the payload: the SAME ObjectRef rides on, with `hops`
        decremented so the final receiver knows to ack the owner. Only
        valid for an envelope recv_fwd returned with hops > 1 (i.e. not
        yet acked); the bytes count as sent — the ref logically carries
        them — which keeps the ring bandwidth-optimality accounting."""
        n = int(env["nbytes"])
        self.stats.bytes_sent += n
        self.stats.sends += 1
        self.stats.zc_sends += 1
        self.stats.zc_bytes_sent += n
        if self.topology.node_of(dst_rank) != self.topology.node_of(self.rank):
            self.stats.bytes_sent_inter += n
        # raylint: disable=leaked-object-ref -- fire-and-forget by design
        self.mailboxes[dst_rank].put.remote(
            key, dict(env, hops=int(env["hops"]) - 1))

    def recv_fwd(self, src_rank: int, key: str, *, op: str = ""):
        """recv() that also returns the zero-copy envelope (or None for
        inline payloads). An envelope with hops > 1 has NOT been acked:
        the caller MUST forward() it onward — the downstream ranks and
        the owner's pinned copy are waiting on that chain."""
        t0 = time.perf_counter()
        self._beacon.arm(op=op, seq=self.seq, key=key,
                         waiting_on_rank=src_rank)
        try:
            out = self._checked_get(
                self.mailbox.take.remote(key, self.timeout_s),
                op=op, budget_s=self.timeout_s)
        finally:
            self._beacon.tick()
            self._beacon.disarm()
        if _is_timeout(out):
            suspects = self.probe_peers()
            self._flight_dump(f"collective:{op or 'op'}:recv_timeout",
                              suspect_ranks=suspects or [src_rank], key=key)
            detail = suspects or "none — peers alive but round stalled"
            raise CollectiveTimeoutError(
                f"collective {op or 'op'} (group {self.name!r}) timed out "
                f"after {self.timeout_s:.1f}s waiting on rank {src_rank} "
                f"(key {key!r}); unresponsive ranks: {detail}",
                group_name=self.name, op=op,
                suspect_ranks=suspects or [src_rank])
        env = None
        if _is_zc(out):
            env = out
            n = int(env["nbytes"])
            # Clock only the store resolve: the mailbox wait above is
            # rendezvous skew (sender not ready), not edge transfer time
            # — folding it in makes bulk edges look an order of magnitude
            # slower than they are and poisons the auto-selector's
            # bandwidth estimate.
            t0 = time.perf_counter()
            try:
                val = ray_tpu.get(env["ref"], timeout=self.timeout_s)
            except (ray_tpu.exceptions.GetTimeoutError,
                    ray_tpu.exceptions.ObjectLostError) as e:
                suspects = self.probe_peers()
                self._flight_dump(f"collective:{op or 'op'}:zc_unresolved",
                                  suspect_ranks=suspects or [src_rank],
                                  key=key)
                raise CollectiveTimeoutError(
                    f"collective {op or 'op'} (group {self.name!r}): "
                    f"zero-copy chunk from rank {src_rank} (key {key!r}) "
                    f"never resolved ({type(e).__name__}); unresponsive "
                    f"ranks: {suspects or [src_rank]}",
                    group_name=self.name, op=op,
                    suspect_ranks=suspects or [src_rank]) from e
            if int(env.get("hops", 1)) <= 1:
                owner = int(env.get("owner", src_rank))
                ack_key = env.get("ack_key", key)
                # raylint: disable=leaked-object-ref -- fire-and-forget ack
                self.mailboxes[owner].put.remote(ACK_PREFIX + ack_key, True)
            out = val
        else:
            n = payload_nbytes(out)
        self.stats.bytes_recv += n
        self.stats.recvs += 1
        # Per-edge observation for the EWMA model. Inline payloads record
        # the full round (rendezvous IS the per-hop cost at small sizes);
        # zero-copy payloads record resolve time only (t0 reset above).
        record_transfer(self.topology.node_of(src_rank),
                        self.topology.node_of(self.rank), n,
                        time.perf_counter() - t0, kind="collective")
        return out, env

    def _checked_get(self, ref, *, op: str, budget_s: float):
        """get() that converts transport failures into CollectiveError."""
        try:
            # modest slack over the server-side timeout: the sentinel is
            # the primary mechanism, this is the belt for a dead mailbox
            return ray_tpu.get(ref, timeout=budget_s + 15.0)
        except (ray_tpu.exceptions.ActorDiedError,
                ray_tpu.exceptions.ActorUnavailableError,
                ray_tpu.exceptions.WorkerCrashedError) as e:
            suspects = self.probe_peers()
            self._flight_dump(f"collective:{op or 'op'}:member_lost",
                              suspect_ranks=suspects, error=repr(e))
            raise CollectiveError(
                f"collective {op or 'op'} (group {self.name!r}) lost a "
                f"member: {e}; unresponsive ranks: {suspects}",
                group_name=self.name, op=op, suspect_ranks=suspects) from e
        except ray_tpu.exceptions.GetTimeoutError as e:
            suspects = self.probe_peers()
            self._flight_dump(f"collective:{op or 'op'}:get_timeout",
                              suspect_ranks=suspects)
            raise CollectiveTimeoutError(
                f"collective {op or 'op'} (group {self.name!r}) timed out "
                f"after {budget_s:.1f}s; unresponsive ranks: {suspects}",
                group_name=self.name, op=op, suspect_ranks=suspects) from e
        except ray_tpu.exceptions.TaskError as e:
            cause = getattr(e, "cause", None)
            if isinstance(cause, (ValueError, CollectiveError)):
                raise cause
            raise

    def _flight_dump(self, reason: str, **extra) -> None:
        """Write the black box on the way into a CollectiveError — the
        ring still holds the rounds leading up to the failure. Never
        lets recording problems mask the collective error itself."""
        try:
            from ray_tpu import _rt
            rt = _rt.get_runtime()
            # Staged zero-copy chunks still pinned awaiting an ack: the
            # dump names WHICH ack each stuck chunk waits on and which
            # rank owes it — the usual culprit in a wedged ring.
            staged = [{"ack_key": k, "nbytes": e[1],
                       "waiter_rank": e[2] if len(e) > 2 else None,
                       "object": e[0].id.hex()[:16]}
                      for k, e in list(self._zc_inflight.items())[:64]]
            rt.flight.dump(reason, extra=dict(
                extra, group=self.name, rank=self.rank, world=self.world,
                seq=self.seq, staged_unacked=staged,
                staged_unacked_bytes=self._zc_bytes))
        except Exception:
            pass

    def probe_peers(self, probe_timeout_s: float = 3.0) -> List[int]:
        """Ping every peer mailbox; return ranks that did not answer."""
        refs, order = [], []
        for r, mbx in self.mailboxes.items():
            if r == self.rank:
                continue
            try:
                refs.append(mbx.ping.remote())
                order.append(r)
            except Exception:
                order.append(r)
                refs.append(None)
        suspects = []
        for r, ref in zip(order, refs):
            if ref is None:
                suspects.append(r)
                continue
            try:
                ray_tpu.get(ref, timeout=probe_timeout_s)
            except Exception:
                suspects.append(r)
        return sorted(suspects)

    # -- lifecycle -------------------------------------------------------

    def next_seq(self) -> int:
        # op boundary: cheap non-blocking reap of zero-copy acks so a
        # steady stream of ops keeps the inflight window near-empty
        self._reap_zc_acks(block=False)
        self.seq += 1
        return self.seq

    def destroy(self):
        """Kill every helper actor this rank can name (idempotent)."""
        mem = _memory.tracker()
        for ref, _, _ in self._zc_inflight.values():
            mem.unpin(ref.id, "await_ack")
        self._zc_inflight.clear()
        self._zc_bytes = 0
        _health.drop_beacon(self._beacon.component)
        for name in ([_actor_name(self.name)]
                     + [_actor_name(self.name, f"_mbx{r}")
                        for r in range(self.world)]):
            try:
                ray_tpu.kill(ray_tpu.get_actor(name))
            except Exception:
                pass
