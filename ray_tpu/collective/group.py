"""Group bootstrap + the peer-to-peer transport every backend rides.

Two kinds of helper actors per group:

- one ``_Coordinator`` (created by rank 0, named ``_collective_{group}``)
  — the legacy gather/broadcast rendezvous. It doubles as the bootstrap
  barrier: every rank allgathers its (node id, mailbox handle) through
  it once, which yields the membership table the ``Topology`` and the
  peer-to-peer backends are built from.
- one ``_Mailbox`` per rank (named ``_collective_{group}_mbx{rank}``) —
  a keyed async slot store. Ring/hierarchical backends move chunks by
  pushing into the *receiver's* mailbox (object-store peer-to-peer:
  sender worker → receiver-mailbox worker, no global fan-in point) and
  the receiver draining its own mailbox. Every ``take`` carries a
  server-side timeout so a dead sender can never park a round forever.

Failure detection: a timed-out ``take``/``exchange`` returns a sentinel
instead of blocking; the client then pings every peer mailbox and raises
``CollectiveTimeoutError`` naming the unresponsive ranks.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.collective.errors import CollectiveError, CollectiveTimeoutError
from ray_tpu.collective.topology import Topology

#: Sentinel dict key marking a server-side timeout reply.
TIMEOUT_KEY = "__col_timeout__"


def _is_timeout(v) -> bool:
    return isinstance(v, dict) and TIMEOUT_KEY in v


# --------------------------------------------------------------------------
# helper actors
# --------------------------------------------------------------------------


@ray_tpu.remote
class _Mailbox:
    """Keyed rendezvous slots for one rank's inbound collective traffic.

    Methods are deliberately SYNCHRONOUS: with max_concurrency > 1 they
    run on the worker's executor threads, where blocking is allowed —
    packaging a large return pins it in the object store via a blocking
    nodelet RPC, which the runtime forbids on the event-loop thread (an
    async ``take`` returning a big chunk would trip that guard)."""

    def __init__(self):
        import threading

        self.slots: Dict[str, Any] = {}
        self.cv = threading.Condition()

    def put(self, key: str, value) -> bool:
        with self.cv:
            self.slots[key] = value
            self.cv.notify_all()
        return True

    def take(self, key: str, timeout_s: float):
        """Block until `key` arrives (or time out → sentinel), then pop it."""
        with self.cv:
            if not self.cv.wait_for(lambda: key in self.slots,
                                    timeout=timeout_s):
                return {TIMEOUT_KEY: key}
            return self.slots.pop(key)

    def ping(self) -> bool:
        return True


@ray_tpu.remote
class _Coordinator:
    """Gather-style rendezvous: every rank contributes, everyone gets the
    combined result (the legacy O(world × bytes) funnel — kept as the
    ``gather`` backend and as the bootstrap allgather)."""

    def __init__(self, world_size: int):
        import threading

        self.world = world_size
        self.rounds: Dict[tuple, dict] = {}
        self.cv = threading.Condition()
        self.bytes_in = 0          # transfer accounting: fan-in volume

    def exchange(self, op: str, seq: int, rank: int, data,
                 timeout_s: float = 300.0):
        """All ranks call with their contribution; returns the combined
        result once everyone arrived, or a timeout sentinel naming the
        ranks that never showed up. Synchronous on purpose — see _Mailbox
        (large combined results must be packaged off the event loop)."""
        key = (op, seq)
        if isinstance(data, np.ndarray):
            self.bytes_in += int(data.nbytes)
        with self.cv:
            slot = self.rounds.setdefault(key, {"parts": {}, "result": None})
            slot["parts"][rank] = data
            if len(slot["parts"]) == self.world:
                slot["result"] = self._combine(op, slot["parts"])
                self.cv.notify_all()
            else:
                def done():
                    s = self.rounds.get(key)
                    return s is None or s["result"] is not None

                if not self.cv.wait_for(done, timeout=timeout_s):
                    missing = [r for r in range(self.world)
                               if r not in slot["parts"]]
                    return {TIMEOUT_KEY: missing}
            result = self.rounds[key]["result"][rank]
            slot["parts"].pop(rank, None)
            if not slot["parts"]:
                self.rounds.pop(key, None)
            return result

    def _combine(self, op: str, parts_by_rank: Dict[int, Any]) -> list:
        parts = [parts_by_rank[r] for r in range(self.world)]
        if op == "allreduce_sum":
            out = parts[0]
            for p in parts[1:]:
                out = out + p
            return [out] * self.world
        if op == "allgather":
            return [list(parts)] * self.world
        if op == "barrier":
            return [True] * self.world
        if op == "broadcast":
            srcs = [p for p in parts if p is not None]
            if not srcs:
                # every rank passed None: a bare StopIteration here would
                # vanish inside the async handler — name the misuse
                raise ValueError(
                    "broadcast: no source rank provided data")
            return [srcs[0]] * self.world
        if op == "reducescatter":
            total = parts[0]
            for p in parts[1:]:
                total = total + p
            return list(np.array_split(total, self.world))
        raise ValueError(op)

    def stats(self) -> dict:
        return {"bytes_in": self.bytes_in}

    def ping(self) -> bool:
        return True


# --------------------------------------------------------------------------
# transfer accounting
# --------------------------------------------------------------------------


def payload_nbytes(obj) -> int:
    """Approximate wire size of a collective payload."""
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if isinstance(obj, (list, tuple)):
        return sum(payload_nbytes(o) for o in obj)
    if isinstance(obj, dict):
        return sum(payload_nbytes(o) for o in obj.values())
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return 8
    try:
        import pickle

        return len(pickle.dumps(obj, protocol=5))
    except Exception:
        return 0


class TransferStats:
    """Per-rank byte accounting — the hook the bandwidth-optimality tests
    and ``bench.py --bench collective`` assert against."""

    def __init__(self):
        self.bytes_sent = 0          # total payload bytes this rank pushed
        self.bytes_sent_inter = 0    # subset that crossed a node boundary
        self.bytes_recv = 0
        self.sends = 0
        self.recvs = 0

    def snapshot(self) -> dict:
        return {"bytes_sent": self.bytes_sent,
                "bytes_sent_inter": self.bytes_sent_inter,
                "bytes_recv": self.bytes_recv,
                "sends": self.sends, "recvs": self.recvs}

    def reset(self):
        self.__init__()


# --------------------------------------------------------------------------
# group context
# --------------------------------------------------------------------------


def _actor_name(group: str, suffix: str = "") -> str:
    return f"_collective_{group}{suffix}"


def _resolve_named(name: str, deadline_s: float = 30.0):
    deadline = time.time() + deadline_s
    while True:
        try:
            return ray_tpu.get_actor(name)
        except ValueError:
            if time.time() > deadline:
                raise
            time.sleep(0.1)


class GroupContext:
    """One rank's view of a collective group: membership, topology,
    mailbox handles, sequencing, transfer accounting.

    Ops must be issued in the same order on every rank (standard
    collective contract); ``seq`` ties the rounds together.
    """

    def __init__(self, name: str, world_size: int, rank: int,
                 timeout_s: float = 60.0):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} outside world of {world_size}")
        self.name = name
        self.world = world_size
        self.rank = rank
        self.timeout_s = float(timeout_s)
        self.seq = 0
        self.stats = TransferStats()
        self.mailboxes: Dict[int, Any] = {}

        coord_name = _actor_name(name)
        mbx_name = _actor_name(name, f"_mbx{rank}")
        # Own mailbox first (peers resolve it by name), then rank 0 brings
        # up the coordinator everyone bootstraps through.
        # Fractional CPU on purpose: 0 < cpu < 1 makes helper actors
        # lane-packable (nodelet._laneable) so a group's whole helper
        # fleet shares one worker process instead of each holding a
        # max_workers_per_node slot — many live groups would otherwise
        # exhaust the worker cap and wedge the next group's bootstrap.
        self.mailbox = _Mailbox.options(
            name=mbx_name, num_cpus=0.01,
            max_concurrency=max(4 * world_size, 16)).remote()
        if rank == 0:
            try:
                self.coord = _Coordinator.options(
                    name=coord_name, num_cpus=0.01,
                    max_concurrency=max(world_size * 2, 4)).remote(world_size)
            except ValueError:
                self.coord = _resolve_named(coord_name)
        else:
            self.coord = _resolve_named(coord_name)

        try:
            node_id = ray_tpu.get_runtime_context().get_node_id()
        except Exception:
            node_id = "local"
        # Bootstrap budget is deliberately generous: joining can pay for
        # several fresh worker-process spawns (~5 s of jax import each,
        # more on a loaded box) before the first rank even registers.
        table = self.coord_exchange(
            "allgather", {"rank": rank, "node": node_id,
                          "mailbox": self.mailbox},
            timeout_s=max(self.timeout_s, 120.0))
        self.mailboxes = {e["rank"]: e["mailbox"] for e in table}
        self.topology = Topology.build({e["rank"]: e["node"] for e in table})

    # -- coordinator path (gather backend + bootstrap) -------------------

    def coord_exchange(self, op: str, data, timeout_s: Optional[float] = None):
        t = self.timeout_s if timeout_s is None else timeout_s
        self.seq += 1
        if isinstance(data, np.ndarray):
            self.stats.bytes_sent += int(data.nbytes)
            self.stats.sends += 1
        out = self._checked_get(
            self.coord.exchange.remote(op, self.seq, self.rank, data, t),
            op=op, budget_s=t)
        if _is_timeout(out):
            raise CollectiveTimeoutError(
                f"collective {op} (group {self.name!r}, seq {self.seq}) "
                f"timed out after {t:.1f}s waiting for ranks {out[TIMEOUT_KEY]}",
                group_name=self.name, op=op, suspect_ranks=out[TIMEOUT_KEY])
        return out

    # -- peer-to-peer path (ring / hier backends) ------------------------

    def send(self, dst_rank: int, key: str, payload) -> None:
        """Fire-and-forget push into dst's mailbox (object-store p2p)."""
        n = payload_nbytes(payload)
        self.stats.bytes_sent += n
        self.stats.sends += 1
        if self.topology.node_of(dst_rank) != self.topology.node_of(self.rank):
            self.stats.bytes_sent_inter += n
        # a lost put surfaces as the receiver's timeout + peer probe
        # raylint: disable=leaked-object-ref -- fire-and-forget by design
        self.mailboxes[dst_rank].put.remote(key, payload)

    def recv(self, src_rank: int, key: str, *, op: str = ""):
        """Blocking take from OWN mailbox of the value `src_rank` pushed."""
        t0 = time.perf_counter()
        out = self._checked_get(
            self.mailbox.take.remote(key, self.timeout_s),
            op=op, budget_s=self.timeout_s)
        if _is_timeout(out):
            suspects = self.probe_peers()
            detail = suspects or "none — peers alive but round stalled"
            raise CollectiveTimeoutError(
                f"collective {op or 'op'} (group {self.name!r}) timed out "
                f"after {self.timeout_s:.1f}s waiting on rank {src_rank} "
                f"(key {key!r}); unresponsive ranks: {detail}",
                group_name=self.name, op=op,
                suspect_ranks=suspects or [src_rank])
        n = payload_nbytes(out)
        self.stats.bytes_recv += n
        self.stats.recvs += 1
        # Per-edge observation for the EWMA model: round time (includes
        # sender skew), which is exactly the cost the collective
        # auto-selector pays per hop on this edge.
        from ray_tpu.observability.edges import record_transfer
        record_transfer(self.topology.node_of(src_rank),
                        self.topology.node_of(self.rank), n,
                        time.perf_counter() - t0, kind="collective")
        return out

    def _checked_get(self, ref, *, op: str, budget_s: float):
        """get() that converts transport failures into CollectiveError."""
        try:
            # modest slack over the server-side timeout: the sentinel is
            # the primary mechanism, this is the belt for a dead mailbox
            return ray_tpu.get(ref, timeout=budget_s + 15.0)
        except (ray_tpu.exceptions.ActorDiedError,
                ray_tpu.exceptions.ActorUnavailableError,
                ray_tpu.exceptions.WorkerCrashedError) as e:
            suspects = self.probe_peers()
            raise CollectiveError(
                f"collective {op or 'op'} (group {self.name!r}) lost a "
                f"member: {e}; unresponsive ranks: {suspects}",
                group_name=self.name, op=op, suspect_ranks=suspects) from e
        except ray_tpu.exceptions.GetTimeoutError as e:
            suspects = self.probe_peers()
            raise CollectiveTimeoutError(
                f"collective {op or 'op'} (group {self.name!r}) timed out "
                f"after {budget_s:.1f}s; unresponsive ranks: {suspects}",
                group_name=self.name, op=op, suspect_ranks=suspects) from e
        except ray_tpu.exceptions.TaskError as e:
            cause = getattr(e, "cause", None)
            if isinstance(cause, (ValueError, CollectiveError)):
                raise cause
            raise

    def probe_peers(self, probe_timeout_s: float = 3.0) -> List[int]:
        """Ping every peer mailbox; return ranks that did not answer."""
        refs, order = [], []
        for r, mbx in self.mailboxes.items():
            if r == self.rank:
                continue
            try:
                refs.append(mbx.ping.remote())
                order.append(r)
            except Exception:
                order.append(r)
                refs.append(None)
        suspects = []
        for r, ref in zip(order, refs):
            if ref is None:
                suspects.append(r)
                continue
            try:
                ray_tpu.get(ref, timeout=probe_timeout_s)
            except Exception:
                suspects.append(r)
        return sorted(suspects)

    # -- lifecycle -------------------------------------------------------

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq

    def destroy(self):
        """Kill every helper actor this rank can name (idempotent)."""
        for name in ([_actor_name(self.name)]
                     + [_actor_name(self.name, f"_mbx{r}")
                        for r in range(self.world)]):
            try:
                ray_tpu.kill(ray_tpu.get_actor(name))
            except Exception:
                pass
