"""Backend registry + auto-selection policy.

Backends are factories taking a ``GroupContext`` and returning an object
with the op surface (allreduce/allgather/broadcast/reducescatter/
barrier). Third parties can plug in via ``register_backend`` — e.g. a
future RDMA or grpc transport — without touching the API layer.

``"auto"`` prices each candidate backend with the measured cost model
(cost.py: hops × edge latency + bytes / edge bandwidth from the
observability/edges EWMA stats, priors until edges warm) and picks the
cheapest — small payloads still land on ``gather`` (one coordinator RTT
beats 2(N−1) ring hops when latency dominates), bulk multi-node on
``hier`` (only node leaders pay the inter-node price), bulk single-node
on ``ring`` — but now because the model says so on this cluster, not
because a static world-size threshold guessed it.

Selection inputs must be identical on every rank: ``select_backend``
here is deterministic in its arguments, and the dispatch path
(api.GroupClient) has rank 0 compute the choice with ITS edge snapshot
and broadcast it, so per-rank snapshot drift can never split a group
across backends.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

#: Historic small-payload cutoff; still the default of the Config knob
#: ``collective_eager_threshold_bytes`` (the inline-transport tier), no
#: longer a backend-selection threshold.
SMALL_PAYLOAD_BYTES = 64 * 1024

_BACKENDS: Dict[str, Callable] = {}


def register_backend(name: str, factory: Callable) -> None:
    _BACKENDS[name] = factory


def get_backend_factory(name: str) -> Callable:
    try:
        return _BACKENDS[name]
    except KeyError:
        raise ValueError(
            f"unknown collective backend {name!r}; "
            f"available: {sorted(_BACKENDS)}") from None


def available_backends() -> List[str]:
    return sorted(_BACKENDS)


def _register_defaults() -> None:
    from ray_tpu.collective.gather import GatherBackend
    from ray_tpu.collective.hier import HierBackend
    from ray_tpu.collective.ring import RingBackend

    register_backend("gather", GatherBackend)
    register_backend("ring", RingBackend)
    register_backend("hier", HierBackend)


_register_defaults()


def select_backend(op: str, world_size: int, topology,
                   payload_bytes: Optional[int] = None,
                   edges: Optional[Dict[str, dict]] = None) -> str:
    """Resolve "auto" to a concrete backend name for one op call by
    pricing the candidates (cost.py). Deterministic in its arguments;
    pass the same `edges` snapshot on every rank (or let the api layer's
    rank-0 agreement round do it for you)."""
    from ray_tpu.collective import cost

    name, _ = cost.choose_backend(op, world_size, topology, payload_bytes,
                                  edges=edges)
    return name
