"""Backend registry + auto-selection policy.

Backends are factories taking a ``GroupContext`` and returning an object
with the op surface (allreduce/allgather/broadcast/reducescatter/
barrier). Third parties can plug in via ``register_backend`` — e.g. a
future RDMA or grpc transport — without touching the API layer.

``"auto"`` picks per call site:

- tiny worlds (≤ 2) and small payloads (< 64 KiB) → ``gather`` — one
  coordinator RTT beats 2(N−1) ring hops when latency dominates;
- large payloads spanning nodes → ``hier`` — only node leaders pay the
  inter-node (DCN-analog) price;
- large payloads on one node → ``ring`` — bandwidth-optimal, no
  single-process fan-in.

Selection inputs must be identical on every rank: world size and
topology always are; payload bytes are used only for ops whose payload
shape is required to match across ranks (allreduce/reducescatter).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

#: Payloads below this take the single-RTT coordinator path under "auto".
SMALL_PAYLOAD_BYTES = 64 * 1024

_BACKENDS: Dict[str, Callable] = {}


def register_backend(name: str, factory: Callable) -> None:
    _BACKENDS[name] = factory


def get_backend_factory(name: str) -> Callable:
    try:
        return _BACKENDS[name]
    except KeyError:
        raise ValueError(
            f"unknown collective backend {name!r}; "
            f"available: {sorted(_BACKENDS)}") from None


def available_backends() -> List[str]:
    return sorted(_BACKENDS)


def _register_defaults() -> None:
    from ray_tpu.collective.gather import GatherBackend
    from ray_tpu.collective.hier import HierBackend
    from ray_tpu.collective.ring import RingBackend

    register_backend("gather", GatherBackend)
    register_backend("ring", RingBackend)
    register_backend("hier", HierBackend)


_register_defaults()


def select_backend(op: str, world_size: int, topology,
                   payload_bytes: Optional[int] = None) -> str:
    """Resolve "auto" to a concrete backend name for one op call."""
    if world_size <= 2:
        return "gather"
    if op in ("allreduce", "reducescatter"):
        if payload_bytes is not None and payload_bytes < SMALL_PAYLOAD_BYTES:
            return "gather"
        if topology is not None and topology.multi_node:
            return "hier"
        return "ring"
    if op == "allgather":
        return "ring"
    if op == "broadcast":
        return "ring"          # tree broadcast: log N depth, no fan-in
    return "gather"            # barrier and anything latency-bound
