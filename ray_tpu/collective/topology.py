"""Group topology: which ranks share a node, and how that maps onto the
mesh axis hierarchy.

Host collectives have two bandwidth domains, exactly like the device
mesh in ``ray_tpu.parallel.mesh``:

- **intra-node** — ranks on the same host exchange through the shared
  shm object store (ICI-adjacent in mesh terms: cheap, wide);
- **inter-node** — ranks on different hosts pay the TCP xfer plane
  (DCN in mesh terms: the axis to economize).

``Topology`` is built once at group init from each rank's GCS node id
(``ray_tpu.get_runtime_context().get_node_id()``) and drives the
hierarchical backend: intra-node traffic is unconstrained, inter-node
traffic is restricted to one leader per node. ``mesh_axis_map`` states
the correspondence with the device-mesh vocabulary so callers that
already hold a mesh can sanity-check that their host group matches the
slice layout (outer/DCN-tolerant axes ↔ inter-node, inner/ICI axes ↔
intra-node — same recipe as ``build_hybrid_mesh``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ray_tpu.parallel.mesh import AXIS_ORDER

#: Mesh axes that tolerate the slow domain (cross-slice DCN ≈ inter-node
#: host traffic) vs. the axes that must stay in the fast domain
#: (ICI ≈ same-host shm). Mirrors DCNSpec's dp/pp-only contract.
DCN_TOLERANT_AXES: Tuple[str, ...] = ("dp", "pp")
ICI_AXES: Tuple[str, ...] = tuple(a for a in AXIS_ORDER
                                  if a not in DCN_TOLERANT_AXES)


@dataclass(frozen=True)
class Topology:
    """Node grouping of a collective group's ranks.

    Attributes:
        world_size: total ranks.
        node_of_rank: rank -> node id (hex string).
        nodes: node ids in deterministic order (sorted by lowest member
            rank, so every rank derives the identical structure).
        members: node id -> sorted ranks on that node.
        leaders: node id -> lowest rank on that node (the rank that
            speaks for the node on the inter-node ring).
    """

    world_size: int
    node_of_rank: Dict[int, str]
    nodes: Tuple[str, ...] = field(default=())
    members: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    leaders: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def build(cls, node_of_rank: Dict[int, str]) -> "Topology":
        world = len(node_of_rank)
        members: Dict[str, List[int]] = {}
        for rank in sorted(node_of_rank):
            members.setdefault(node_of_rank[rank], []).append(rank)
        nodes = tuple(sorted(members, key=lambda n: members[n][0]))
        return cls(
            world_size=world,
            node_of_rank=dict(node_of_rank),
            nodes=nodes,
            members={n: tuple(r) for n, r in members.items()},
            leaders={n: members[n][0] for n in nodes},
        )

    # -- queries --------------------------------------------------------

    def node_of(self, rank: int) -> str:
        return self.node_of_rank[rank]

    def peers_on_node(self, rank: int) -> Tuple[int, ...]:
        return self.members[self.node_of(rank)]

    def leader_of(self, rank: int) -> int:
        return self.leaders[self.node_of(rank)]

    def is_leader(self, rank: int) -> bool:
        return self.leader_of(rank) == rank

    def leader_ranks(self) -> Tuple[int, ...]:
        """Leaders in node order — the inter-node ring membership."""
        return tuple(self.leaders[n] for n in self.nodes)

    @property
    def num_nodes(self) -> int:
        return len(self.nodes)

    @property
    def multi_node(self) -> bool:
        return len(self.nodes) > 1

    # -- mesh mapping ---------------------------------------------------

    def mesh_axis_map(self) -> Dict[str, Dict[str, object]]:
        """Map the topology onto the device-mesh axis hierarchy.

        Returns {"inter_node": {...}, "intra_node": {...}} where each
        scope names its size and the mesh axes whose collectives belong
        in that bandwidth domain. A host group backing a hybrid mesh
        should keep the inter_node factor aligned with the mesh's
        DCN-tolerant axes (dp/pp) — same invariant DCNSpec enforces for
        device collectives.
        """
        intra_sizes = {len(self.members[n]) for n in self.nodes}
        return {
            "inter_node": {"size": self.num_nodes,
                           "axes": list(DCN_TOLERANT_AXES)},
            "intra_node": {"size": (max(intra_sizes) if intra_sizes else 0),
                           "uniform": len(intra_sizes) <= 1,
                           "axes": list(ICI_AXES)},
        }

    def compatible_with_mesh(self, mesh) -> bool:
        """True if the inter-node factor divides the mesh's DCN-tolerant
        axis product — i.e. this host group can carry the mesh's
        cross-slice exchanges without putting an ICI-only axis on DCN."""
        try:
            dcn_product = 1
            for a in DCN_TOLERANT_AXES:
                dcn_product *= int(mesh.shape[a])
        except Exception:
            return False
        return self.num_nodes <= 1 or dcn_product % self.num_nodes == 0
