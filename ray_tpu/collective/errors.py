"""Typed failure taxonomy for host collectives.

A collective round is a distributed rendezvous: if a member dies (or
stalls past the group's timeout) every surviving rank must surface a
typed error instead of blocking forever inside ``ray_tpu.get``. The
reference framework leans on NCCL/Gloo transport errors for this; here
detection is explicit — per-round timeouts plus a liveness probe of the
peers' mailboxes — and everything funnels into ``CollectiveError``.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ray_tpu.core.status import RayTpuError


class CollectiveError(RayTpuError):
    """A host-collective round failed (member death, timeout, bad input).

    Attributes:
        group_name: collective group the failed round belonged to.
        op: operation in flight ("allreduce", "barrier", ...).
        suspect_ranks: ranks whose mailbox/coordinator did not respond to
            the post-timeout liveness probe — the likely casualties.
    """

    def __init__(self, msg: str, *, group_name: str = "",
                 op: str = "", suspect_ranks: Optional[Sequence[int]] = None):
        super().__init__(msg)
        self.group_name = group_name
        self.op = op
        self.suspect_ranks = list(suspect_ranks or [])

    def __reduce__(self):   # survive the TaskError pickling hop
        return (_rebuild, (self.args[0] if self.args else "",
                           self.group_name, self.op, self.suspect_ranks))


def _rebuild(msg, group_name, op, suspect_ranks):
    return CollectiveError(msg, group_name=group_name, op=op,
                           suspect_ranks=suspect_ranks)


class CollectiveTimeoutError(CollectiveError, TimeoutError):
    """A round did not complete within the group's ``timeout_s``."""

    def __reduce__(self):
        return (_rebuild_timeout, (self.args[0] if self.args else "",
                                   self.group_name, self.op,
                                   self.suspect_ranks))


def _rebuild_timeout(msg, group_name, op, suspect_ranks):
    return CollectiveTimeoutError(msg, group_name=group_name, op=op,
                                  suspect_ranks=suspect_ranks)
