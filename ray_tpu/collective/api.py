"""Public host-collective API.

Reference: python/ray/util/collective/collective.py (GroupManager:40,
init_collective_group:120, allreduce:258, barrier:298, broadcast:373,
allgather:423, reducescatter:472). Backends are host-topology-aware
algorithms over the object store (registry.py) instead of NCCL/Gloo
process groups; *device* collectives stay inside jitted programs
(ray_tpu.parallel — see ARCHITECTURE.md "Host collectives").

Contracts:

- Every rank must issue the same ops in the same order on a group
  (standard collective semantics; rounds are tied by sequence number).
- SUM is the reduction (same as the legacy coordinator).
- Payloads: numpy arrays, scalars, or pytrees (nested dict/list/tuple)
  of them for allreduce; arbitrary picklable values for
  allgather/broadcast.
- A member death or stall surfaces as ``CollectiveError`` (usually the
  ``CollectiveTimeoutError`` subclass, naming suspect ranks) on every
  surviving rank within roughly the group's ``timeout_s`` — no deadlock.
- ``*_async`` variants return ``concurrent.futures.Future`` and run on a
  per-group thread, overlapping host communication with caller compute;
  per group they execute in submission order.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.collective import pytree as _pt
from ray_tpu.util import tracing as _tracing
from ray_tpu.collective.errors import CollectiveError, CollectiveTimeoutError
from ray_tpu.collective.group import GroupContext
from ray_tpu.collective.registry import (available_backends,
                                         get_backend_factory,
                                         register_backend, select_backend)
from ray_tpu.collective.topology import Topology

#: Keyed by (calling actor id, group name), NOT group name alone:
#: lane-packed fractional-CPU actors share a worker process, so
#: per-process state would let rank N's init clobber rank M's (their
#: allreduce then deadlocks waiting for ranks that can never arrive).
_groups: Dict[tuple, "GroupClient"] = {}


def _ctx() -> Optional[str]:
    try:
        return ray_tpu.get_runtime_context().get_actor_id()
    except Exception:
        return None


def _on_actor_teardown(actor_id_hex: str) -> None:
    """Lane actors die without their process dying: drop their group
    clients so a churning fleet cannot grow _groups unboundedly."""
    for key in [k for k in _groups if k[0] == actor_id_hex]:
        g = _groups.pop(key, None)
        if g is not None:
            g.close_local()


from ray_tpu.core.runtime import actor_teardown_hooks as _hooks  # noqa: E402

_hooks.append(_on_actor_teardown)


class GroupClient:
    """One rank's membership in one collective group."""

    def __init__(self, name: str, world_size: int, rank: int,
                 backend: str = "auto", timeout_s: float = 60.0,
                 pipeline_chunks: int = 4):
        if backend != "auto":
            get_backend_factory(backend)     # fail fast on unknown names
        self.ctx = GroupContext(name, world_size, rank, timeout_s=timeout_s)
        self.requested_backend = backend
        self.pipeline_chunks = pipeline_chunks
        self._instances: Dict[str, Any] = {}
        self._op_lock = threading.Lock()     # serializes sync vs async ops
        self._executor: Optional[ThreadPoolExecutor] = None

    # -- plumbing --------------------------------------------------------

    @property
    def name(self) -> str:
        return self.ctx.name

    @property
    def rank(self) -> int:
        return self.ctx.rank

    @property
    def world(self) -> int:
        return self.ctx.world

    @property
    def topology(self) -> Topology:
        return self.ctx.topology

    def _backend(self, op: str, payload_bytes: Optional[int] = None):
        name = self.requested_backend
        if name == "auto":
            name = select_backend(op, self.world, self.ctx.topology,
                                  payload_bytes)
        inst = self._instances.get(name)
        if inst is None:
            factory = get_backend_factory(name)
            try:
                inst = factory(self.ctx, pipeline_chunks=self.pipeline_chunks)
            except TypeError:
                inst = factory(self.ctx)
            self._instances[name] = inst
        return inst

    def _submit(self, fn, *args) -> Future:
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f"col-{self.name}-r{self.rank}")
        return self._executor.submit(fn, *args)

    def close_local(self):
        """Release this rank's local resources (not the group actors)."""
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    # -- ops -------------------------------------------------------------

    def _span(self, op: str):
        """Collective rounds are timeline spans (no-op when tracing is
        off) — they land in the recording worker's lane next to its
        tasks."""
        return _tracing.span(f"collective::{op}",
                             {"group": self.name, "rank": self.rank,
                              "world": self.world})

    def allreduce(self, tensor):
        with self._op_lock, self._span("allreduce"):
            if _pt.is_leaf(tensor):
                arr = np.asarray(tensor)
                return self._backend("allreduce", arr.nbytes).allreduce(arr)
            leaves, treedef = _pt.tree_flatten(tensor)
            buffers, layout = _pt.pack_leaves(leaves)
            reduced = [self._backend("allreduce", b.nbytes).allreduce(b)
                       for b in buffers]
            return _pt.tree_unflatten(treedef,
                                      _pt.unpack_leaves(reduced, layout))

    def allgather(self, value) -> List[Any]:
        with self._op_lock, self._span("allgather"):
            return self._backend("allgather").allgather(value)

    def broadcast(self, value, src_rank: int = 0):
        if not (0 <= src_rank < self.world):
            raise ValueError(f"broadcast: src_rank {src_rank} outside "
                             f"world of {self.world}")
        with self._op_lock, self._span("broadcast"):
            data = value if self.rank == src_rank else None
            return self._backend("broadcast").broadcast(data, src_rank)

    def reducescatter(self, tensor) -> np.ndarray:
        arr = np.asarray(tensor)
        if arr.ndim == 0:
            raise ValueError("reducescatter: payload must have at least "
                             "one dimension to scatter over")
        if arr.shape[0] % self.world:
            # the legacy coordinator silently returned ragged
            # np.array_split chunks here — refuse instead
            raise ValueError(
                f"reducescatter: leading dim {arr.shape[0]} is not "
                f"divisible by world_size {self.world}; pad the payload "
                "or pick a scatterable batch dimension")
        with self._op_lock, self._span("reducescatter"):
            return self._backend("reducescatter", arr.nbytes).reducescatter(arr)

    def barrier(self) -> None:
        with self._op_lock, self._span("barrier"):
            self._backend("barrier").barrier()

    def destroy(self):
        self.close_local()
        self.ctx.destroy()


# --------------------------------------------------------------------------
# module-level API (the surface util/collective.py re-exports)
# --------------------------------------------------------------------------


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default", *,
                          backend: str = "auto",
                          timeout_s: float = 60.0,
                          pipeline_chunks: int = 4) -> None:
    """Join `group_name` as `rank` of `world_size` (ref: collective.py:120).

    backend: "auto" | "gather" | "ring" | "hier" | any registered name.
    timeout_s: per-round deadline before surviving ranks raise
        ``CollectiveTimeoutError`` (member-failure detection).
    """
    _groups[(_ctx(), group_name)] = GroupClient(
        group_name, world_size, rank, backend=backend,
        timeout_s=timeout_s, pipeline_chunks=pipeline_chunks)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear the group down: drops this rank's client AND kills the named
    helper actors (coordinator + mailboxes) so repeated init/destroy
    cycles cannot leak one named actor per group name. Call after the
    fleet is done with the group (any rank may run the reaping)."""
    g = _groups.pop((_ctx(), group_name), None)
    if g is not None:
        g.destroy()
        return
    # No local client (e.g. driver-side cleanup after members died):
    # reap the named actors directly.
    for suffix in [""] + [f"_mbx{r}" for r in range(1024)]:
        name = f"_collective_{group_name}{suffix}"
        try:
            ray_tpu.kill(ray_tpu.get_actor(name))
        except ValueError:
            if suffix != "":
                break                    # contiguous ranks: first gap ends it
        except Exception:
            pass


def _group(name: str) -> GroupClient:
    key = (_ctx(), name)
    g = _groups.get(key)
    if g is not None:
        return g
    # Helper threads an actor spawns itself start with a fresh context
    # (no actor id). If exactly ONE client for this group name lives in
    # the process, that use is unambiguous — honor it (the per-process
    # reference semantics). Multiple same-name clients (lane-packed
    # ranks) make a context-less call genuinely ambiguous.
    candidates = [g for (a, n), g in _groups.items() if n == name]
    if len(candidates) == 1:
        return candidates[0]
    if candidates:
        raise RuntimeError(
            f"collective group {name!r}: ambiguous caller — "
            f"{len(candidates)} lane-packed actors initialized this "
            "group in one process, and this call carries no actor "
            "context (e.g. a self-spawned thread). Call from an actor "
            "method, or propagate contextvars into the thread")
    raise RuntimeError(f"collective group {name!r} not initialized")


def allreduce(tensor, group_name: str = "default"):
    """SUM allreduce of an array or pytree (ref: collective.py:258)."""
    return _group(group_name).allreduce(tensor)


def allgather(tensor, group_name: str = "default") -> List[Any]:
    return _group(group_name).allgather(tensor)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(tensor, src_rank)


def reducescatter(tensor, group_name: str = "default") -> np.ndarray:
    return _group(group_name).reducescatter(tensor)


def barrier(group_name: str = "default") -> None:
    _group(group_name).barrier()


# -- async variants (compute/comm overlap) ---------------------------------


def allreduce_async(tensor, group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.allreduce, tensor)


def allgather_async(tensor, group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.allgather, tensor)


def broadcast_async(tensor, src_rank: int = 0,
                    group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.broadcast, tensor, src_rank)


def reducescatter_async(tensor, group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.reducescatter, tensor)


def barrier_async(group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.barrier)


# -- introspection ---------------------------------------------------------


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world


def get_group_topology(group_name: str = "default") -> Topology:
    return _group(group_name).topology


def transfer_stats(group_name: str = "default") -> dict:
    """This rank's byte accounting (the bandwidth-optimality hook)."""
    return _group(group_name).ctx.stats.snapshot()


def reset_transfer_stats(group_name: str = "default") -> None:
    _group(group_name).ctx.stats.reset()


def coordinator_stats(group_name: str = "default") -> dict:
    """The gather coordinator's fan-in accounting (bytes_in)."""
    g = _group(group_name)
    return ray_tpu.get(g.ctx.coord.stats.remote(), timeout=30)
