"""Public host-collective API.

Reference: python/ray/util/collective/collective.py (GroupManager:40,
init_collective_group:120, allreduce:258, barrier:298, broadcast:373,
allgather:423, reducescatter:472). Backends are host-topology-aware
algorithms over the object store (registry.py) instead of NCCL/Gloo
process groups; *device* collectives stay inside jitted programs
(ray_tpu.parallel — see ARCHITECTURE.md "Host collectives").

Contracts:

- Every rank must issue the same ops in the same order on a group
  (standard collective semantics; rounds are tied by sequence number).
- SUM is the reduction (same as the legacy coordinator).
- Payloads: numpy arrays, scalars, or pytrees (nested dict/list/tuple)
  of them for allreduce; arbitrary picklable values for
  allgather/broadcast.
- A member death or stall surfaces as ``CollectiveError`` (usually the
  ``CollectiveTimeoutError`` subclass, naming suspect ranks) on every
  surviving rank within roughly the group's ``timeout_s`` — no deadlock.
- ``*_async`` variants return ``concurrent.futures.Future`` and run on a
  per-group thread, overlapping host communication with caller compute;
  per group they execute in submission order.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.collective import cost as _cost
from ray_tpu.collective import pytree as _pt
from ray_tpu.util import tracing as _tracing
from ray_tpu.collective.errors import CollectiveError, CollectiveTimeoutError
from ray_tpu.collective.group import GroupContext
from ray_tpu.collective.registry import (available_backends,
                                         get_backend_factory,
                                         register_backend, select_backend)
from ray_tpu.collective.topology import Topology

#: Keyed by (calling actor id, group name), NOT group name alone:
#: lane-packed fractional-CPU actors share a worker process, so
#: per-process state would let rank N's init clobber rank M's (their
#: allreduce then deadlocks waiting for ranks that can never arrive).
_groups: Dict[tuple, "GroupClient"] = {}


def _ctx() -> Optional[str]:
    try:
        return ray_tpu.get_runtime_context().get_actor_id()
    except Exception:
        return None


def _on_actor_teardown(actor_id_hex: str) -> None:
    """Lane actors die without their process dying: drop their group
    clients so a churning fleet cannot grow _groups unboundedly."""
    for key in [k for k in _groups if k[0] == actor_id_hex]:
        g = _groups.pop(key, None)
        if g is not None:
            g.close_local()


from ray_tpu.core.runtime import actor_teardown_hooks as _hooks  # noqa: E402

_hooks.append(_on_actor_teardown)


class GroupClient:
    """One rank's membership in one collective group."""

    #: A cached backend decision is re-priced after this many uses —
    #: frequent enough to track edge-model drift within a workload,
    #: rare enough that the agreement round's coordinator RTT amortizes.
    REFRESH_EVERY = 64

    def __init__(self, name: str, world_size: int, rank: int,
                 backend: str = "auto", timeout_s: float = 60.0,
                 pipeline_chunks: int = 4, transport: str = "auto"):
        if backend != "auto":
            get_backend_factory(backend)     # fail fast on unknown names
        self.ctx = GroupContext(name, world_size, rank, timeout_s=timeout_s,
                                transport=transport)
        self.requested_backend = backend
        self.pipeline_chunks = pipeline_chunks
        self._instances: Dict[str, Any] = {}
        #: (op, payload log2-bucket) → agreed decision dict. Identical on
        #: every rank by construction (rank 0 broadcasts its choice).
        self._decisions: Dict[tuple, dict] = {}
        self._op_lock = threading.Lock()     # serializes sync vs async ops
        self._executor: Optional[ThreadPoolExecutor] = None

    # -- plumbing --------------------------------------------------------

    @property
    def name(self) -> str:
        return self.ctx.name

    @property
    def rank(self) -> int:
        return self.ctx.rank

    @property
    def world(self) -> int:
        return self.ctx.world

    @property
    def topology(self) -> Topology:
        return self.ctx.topology

    def _instance(self, name: str):
        inst = self._instances.get(name)
        if inst is None:
            factory = get_backend_factory(name)
            try:
                inst = factory(self.ctx, pipeline_chunks=self.pipeline_chunks)
            except TypeError:
                inst = factory(self.ctx)
            self._instances[name] = inst
        return inst

    def _choose(self, op: str, payload_bytes: Optional[int] = None):
        """(backend name, decision info) for one op call.

        With backend="auto" the choice comes from the measured cost
        model, agreed across ranks: rank 0 prices the candidates with
        ITS edge-stats snapshot and coordinator EWMA and broadcasts the
        result — per-rank snapshot drift can never split the group
        across backends. Decisions cache per (op, payload bucket) so the
        agreement RTT amortizes; every rank's cache and use counters
        advance in lockstep (same op stream), so refreshes line up too."""
        if self.requested_backend != "auto":
            return self.requested_backend, {
                "backend": self.requested_backend, "source": "requested"}
        key = (op, _cost.payload_bucket(payload_bytes))
        dec = self._decisions.get(key)
        if dec is not None and dec["uses"] < self.REFRESH_EVERY:
            dec["uses"] += 1
            return dec["backend"], dec
        dec = self._agree(op, payload_bytes)
        dec["uses"] = 1
        self._decisions[key] = dec
        return dec["backend"], dec

    def _agree(self, op: str, payload_bytes: Optional[int]) -> dict:
        ctx = self.ctx
        if self.world == 1:
            _, info = _cost.choose_backend(op, 1, ctx.topology, payload_bytes)
            return dict(info)
        chosen = None
        if ctx.rank == 0:
            try:
                from ray_tpu.observability.edges import edge_stats

                edges = edge_stats()
            except Exception:
                edges = {}
            _, info = _cost.choose_backend(
                op, self.world, ctx.topology, payload_bytes, edges=edges,
                coord_lat=ctx.coord_lat_ewma, coord_bw=ctx.coord_bw_ewma)
            chosen = dict(info)
        # one coordinator RTT ties the round; every rank must pass here
        # (same op stream), so this cannot deadlock
        return dict(ctx.coord_exchange("broadcast", chosen))

    def _submit(self, fn, *args) -> Future:
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f"col-{self.name}-r{self.rank}")
        return self._executor.submit(fn, *args)

    def close_local(self):
        """Release this rank's local resources (not the group actors)."""
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    # -- ops -------------------------------------------------------------

    def _span(self, op: str, decision: Optional[dict] = None):
        """Collective rounds are timeline spans (no-op when tracing is
        off) — they land in the recording worker's lane next to its
        tasks, carrying the auto-selector's decision."""
        args = {"group": self.name, "rank": self.rank, "world": self.world}
        if decision:
            args["backend"] = decision.get("backend")
            args["decision_source"] = decision.get("source")
            costs = decision.get("costs_ms")
            if costs:
                args["predicted_cost_ms"] = costs.get(decision.get("backend"))
        return _tracing.span(f"collective::{op}", args)

    def allreduce(self, tensor):
        with self._op_lock:
            if _pt.is_leaf(tensor):
                arr = np.asarray(tensor)
                name, dec = self._choose("allreduce", arr.nbytes)
                with self._span("allreduce", dec):
                    return self._instance(name).allreduce(arr)
            leaves, treedef = _pt.tree_flatten(tensor)
            buffers, layout = _pt.pack_leaves(leaves)
            name, dec = self._choose(
                "allreduce", buffers[0].nbytes if buffers else None)
            with self._span("allreduce", dec):
                # per-buffer choice (packed buffers differ in size); the
                # duplicate first-buffer _choose is a cache hit and every
                # rank repeats it identically, so counters stay in step
                reduced = [
                    self._instance(self._choose("allreduce", b.nbytes)[0])
                    .allreduce(b) for b in buffers]
                return _pt.tree_unflatten(
                    treedef, _pt.unpack_leaves(reduced, layout))

    def allgather(self, value) -> List[Any]:
        with self._op_lock:
            name, dec = self._choose("allgather")
            with self._span("allgather", dec):
                return self._instance(name).allgather(value)

    def broadcast(self, value, src_rank: int = 0):
        if not (0 <= src_rank < self.world):
            raise ValueError(f"broadcast: src_rank {src_rank} outside "
                             f"world of {self.world}")
        with self._op_lock:
            name, dec = self._choose("broadcast")
            with self._span("broadcast", dec):
                data = value if self.rank == src_rank else None
                return self._instance(name).broadcast(data, src_rank)

    def reducescatter(self, tensor) -> np.ndarray:
        arr = np.asarray(tensor)
        if arr.ndim == 0:
            raise ValueError("reducescatter: payload must have at least "
                             "one dimension to scatter over")
        if arr.shape[0] % self.world:
            # the legacy coordinator silently returned ragged
            # np.array_split chunks here — refuse instead
            raise ValueError(
                f"reducescatter: leading dim {arr.shape[0]} is not "
                f"divisible by world_size {self.world}; pad the payload "
                "or pick a scatterable batch dimension")
        with self._op_lock:
            name, dec = self._choose("reducescatter", arr.nbytes)
            with self._span("reducescatter", dec):
                return self._instance(name).reducescatter(arr)

    def barrier(self) -> None:
        with self._op_lock:
            name, dec = self._choose("barrier")
            with self._span("barrier", dec):
                self._instance(name).barrier()

    def destroy(self):
        self.close_local()
        self.ctx.destroy()


# --------------------------------------------------------------------------
# module-level API (the surface util/collective.py re-exports)
# --------------------------------------------------------------------------


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default", *,
                          backend: str = "auto",
                          timeout_s: float = 60.0,
                          pipeline_chunks: int = 4,
                          transport: str = "auto") -> None:
    """Join `group_name` as `rank` of `world_size` (ref: collective.py:120).

    backend: "auto" | "gather" | "ring" | "hier" | any registered name.
    timeout_s: per-round deadline before surviving ranks raise
        ``CollectiveTimeoutError`` (member-failure detection).
    transport: "auto" (Config-threshold tiering: inline below the eager
        threshold, zero-copy object-store refs above the zero-copy
        threshold) | "mailbox" (force everything inline+chunked — the
        legacy transport) | "zerocopy" (force every ndarray/bytes chunk
        through the store) | "eager" (force single inline messages).
        Every rank of a group must pass the same value.
    """
    _groups[(_ctx(), group_name)] = GroupClient(
        group_name, world_size, rank, backend=backend,
        timeout_s=timeout_s, pipeline_chunks=pipeline_chunks,
        transport=transport)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear the group down: drops this rank's client AND kills the named
    helper actors (coordinator + mailboxes) so repeated init/destroy
    cycles cannot leak one named actor per group name. Call after the
    fleet is done with the group (any rank may run the reaping)."""
    g = _groups.pop((_ctx(), group_name), None)
    if g is not None:
        g.destroy()
        return
    # No local client (e.g. driver-side cleanup after members died):
    # reap the named actors directly.
    for suffix in [""] + [f"_mbx{r}" for r in range(1024)]:
        name = f"_collective_{group_name}{suffix}"
        try:
            ray_tpu.kill(ray_tpu.get_actor(name))
        except ValueError:
            if suffix != "":
                break                    # contiguous ranks: first gap ends it
        except Exception:
            pass


def generation_name(group_name: str, generation: int) -> str:
    """The name incarnation `generation` of a logical group uses for its
    helper actors. Group membership is static — a resize means a NEW
    group — so elastic rebuilds join `name@g<N>` instead of racing the
    previous incarnation's coordinator/mailbox actors on `name`."""
    return group_name if generation <= 0 else f"{group_name}@g{generation}"


def reform_collective_group(group_name: str, generation: int) -> str:
    """Re-form a logical group for a new (possibly shrunken) membership.

    Driver-side half of an elastic rebuild: tear down the PREVIOUS
    incarnation's named helper actors (its members may all be dead, so
    the reap must not require a local client — destroy_collective_group
    handles that) and return the generation-qualified name the new
    members must pass to init_collective_group. Idempotent: reaping a
    name with no actors is a no-op."""
    destroy_collective_group(generation_name(group_name, generation - 1))
    return generation_name(group_name, generation)


def _group(name: str) -> GroupClient:
    key = (_ctx(), name)
    g = _groups.get(key)
    if g is not None:
        return g
    # Helper threads an actor spawns itself start with a fresh context
    # (no actor id). If exactly ONE client for this group name lives in
    # the process, that use is unambiguous — honor it (the per-process
    # reference semantics). Multiple same-name clients (lane-packed
    # ranks) make a context-less call genuinely ambiguous.
    candidates = [g for (a, n), g in _groups.items() if n == name]
    if len(candidates) == 1:
        return candidates[0]
    if candidates:
        raise RuntimeError(
            f"collective group {name!r}: ambiguous caller — "
            f"{len(candidates)} lane-packed actors initialized this "
            "group in one process, and this call carries no actor "
            "context (e.g. a self-spawned thread). Call from an actor "
            "method, or propagate contextvars into the thread")
    raise RuntimeError(f"collective group {name!r} not initialized")


def allreduce(tensor, group_name: str = "default"):
    """SUM allreduce of an array or pytree (ref: collective.py:258)."""
    return _group(group_name).allreduce(tensor)


def allgather(tensor, group_name: str = "default") -> List[Any]:
    return _group(group_name).allgather(tensor)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(tensor, src_rank)


def reducescatter(tensor, group_name: str = "default") -> np.ndarray:
    return _group(group_name).reducescatter(tensor)


def barrier(group_name: str = "default") -> None:
    _group(group_name).barrier()


# -- async variants (compute/comm overlap) ---------------------------------


def allreduce_async(tensor, group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.allreduce, tensor)


def allgather_async(tensor, group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.allgather, tensor)


def broadcast_async(tensor, src_rank: int = 0,
                    group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.broadcast, tensor, src_rank)


def reducescatter_async(tensor, group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.reducescatter, tensor)


def barrier_async(group_name: str = "default") -> Future:
    g = _group(group_name)
    return g._submit(g.barrier)


# -- introspection ---------------------------------------------------------


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world


def get_group_topology(group_name: str = "default") -> Topology:
    return _group(group_name).topology


def transfer_stats(group_name: str = "default") -> dict:
    """This rank's byte accounting (the bandwidth-optimality hook)."""
    return _group(group_name).ctx.stats.snapshot()


def reset_transfer_stats(group_name: str = "default") -> None:
    _group(group_name).ctx.stats.reset()


def coordinator_stats(group_name: str = "default") -> dict:
    """The gather coordinator's fan-in accounting (bytes_in)."""
    g = _group(group_name)
    return ray_tpu.get(g.ctx.coord.stats.remote(), timeout=30)


def group_stats(group_name: str = "default") -> dict:
    """This rank's full collective picture: transfer accounting, the
    transport tiering in effect, and every auto-selection decision (the
    chosen backend + the cost model's predictions behind it)."""
    g = _group(group_name)
    ctx = g.ctx
    return {
        "group": g.name,
        "rank": g.rank,
        "world": g.world,
        "requested_backend": g.requested_backend,
        "transfer": ctx.stats.snapshot(),
        "transport": {
            "mode": ctx.transport,
            "eager_threshold_bytes": ctx.eager_threshold,
            "zerocopy_threshold_bytes": ctx.zc_threshold,
            "zc_inflight_chunks": len(ctx._zc_inflight),
            "zc_inflight_bytes": ctx._zc_bytes,
        },
        "coordinator_model": {
            "latency_ewma_s": ctx.coord_lat_ewma,
            "bandwidth_ewma_bps": ctx.coord_bw_ewma,
        },
        "decisions": {f"{op}@{bucket}": dict(d)
                      for (op, bucket), d in g._decisions.items()},
    }
