"""Ring backend: bandwidth-optimal chunked collectives (``"ring"``).

Allreduce = reduce-scatter + all-gather around a logical ring
(Patarasuk & Yuan 2009): each rank sends 2·(N−1)/N of the payload total
— independent of N — instead of the gather backend's N× fan-in through
one coordinator. A per-step block in the inline-mailbox band is further
split into ``pipeline_chunks`` sub-chunks whose sends are all issued
before the first receive is drained, so transport overlaps with the
local accumulate (chunked pipelining); blocks in the zero-copy band go
as one store object per step, and in the all-gather phase the SAME
ObjectRef is forwarded hop-to-hop instead of re-staged (see
``ring_allreduce_flat`` phase 2).

Broadcast and barrier use a binary tree (log N rounds) rather than the
ring — latency-bound ops don't benefit from ring bandwidth.

The module-level helpers take an explicit ``ring_ranks`` subgroup and a
caller-supplied ``tag`` (which must embed the op's seq) so the
hierarchical backend can reuse them for its leader-only ring without
desynchronizing sequence numbers across ranks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from ray_tpu.collective.group import GroupContext


def _bounds(n: int, parts: int) -> List[Tuple[int, int]]:
    """np.array_split boundary arithmetic over a flat length."""
    q, r = divmod(n, parts)
    out, acc = [], 0
    for i in range(parts):
        size = q + (1 if i < r else 0)
        out.append((acc, acc + size))
        acc += size
    return out


def _sub_bounds(lo: int, hi: int, parts: int) -> List[Tuple[int, int]]:
    n = hi - lo
    if n <= 0:
        return [(lo, lo)]
    parts = max(1, min(parts, n))
    return [(lo + a, lo + b) for a, b in _bounds(n, parts)]


def _wire_subchunks(ctx: GroupContext, lo: int, hi: int, itemsize: int,
                    pipeline_chunks: int) -> List[Tuple[int, int]]:
    """Sub-chunk bounds for one per-step block, honoring the transport
    tiers: a block below ``collective_eager_threshold_bytes`` goes as ONE
    inline message — at small sizes the per-chunk fixed costs (actor RPC
    + pickle) dominate and pipelining only multiplies them — and a block
    big enough for the zero-copy tier ALSO goes as one piece, because the
    object store already decouples transfer from the mailbox rendezvous
    (sub-chunking a ref-mailed block would just multiply put/take/ack
    round-trips). Pipelining earns its keep only in the middle (inline
    mailbox) band. Sender and receiver compute this from identical
    sizes, so keys agree across ranks."""
    block = (hi - lo) * itemsize
    if block < ctx.eager_threshold:
        return [(lo, hi if hi > lo else lo)]
    if ctx.zc_threshold is not None and block >= ctx.zc_threshold:
        return [(lo, hi)]
    return _sub_bounds(lo, hi, pipeline_chunks)


def ring_allreduce_flat(ctx: GroupContext, buf: np.ndarray,
                        ring_ranks: Sequence[int], tag: str,
                        pipeline_chunks: int = 4) -> np.ndarray:
    """In-place SUM allreduce of a flat 1-D buffer over `ring_ranks`.

    Only the listed ranks may call; all of them must. Returns `buf`.
    """
    ranks = list(ring_ranks)
    n = len(ranks)
    if n == 1:
        return buf
    pos = ranks.index(ctx.rank)
    right = ranks[(pos + 1) % n]
    left = ranks[(pos - 1) % n]
    chunks = _bounds(buf.size, n)

    # phase 1 — reduce-scatter: after n-1 steps rank at `pos` holds
    # chunk `pos` fully reduced
    for step in range(n - 1):
        send_c = (pos - 1 - step) % n
        recv_c = (pos - 2 - step) % n
        send_subs = _wire_subchunks(ctx, *chunks[send_c], buf.itemsize,
                                    pipeline_chunks)
        recv_subs = _wire_subchunks(ctx, *chunks[recv_c], buf.itemsize,
                                    pipeline_chunks)
        ctx.send_many(right, [(f"{tag}:rs:{step}:{i}", buf[a:b])
                              for i, (a, b) in enumerate(send_subs)])
        for i, (a, b) in enumerate(recv_subs):
            part = ctx.recv(left, f"{tag}:rs:{step}:{i}", op="allreduce")
            if b > a:
                buf[a:b] += part

    # phase 2 — all-gather: circulate the reduced chunks. A zero-copy
    # chunk is put() into the store ONCE by its owner (hops=n-1) and the
    # same ObjectRef is forward()ed around the ring — the n-1 re-puts
    # (and their memcpys + pin RPCs) the naive loop would pay collapse
    # into envelope relays; only the final hop acks the owner.
    held: Dict[int, dict] = {}
    for step in range(n - 1):
        send_c = (pos - step) % n
        recv_c = (pos - step - 1) % n
        send_subs = _wire_subchunks(ctx, *chunks[send_c], buf.itemsize,
                                    pipeline_chunks)
        recv_subs = _wire_subchunks(ctx, *chunks[recv_c], buf.itemsize,
                                    pipeline_chunks)
        if step == 0:
            ctx.send_many(right, [(f"{tag}:ag:{step}:{i}", buf[a:b])
                                  for i, (a, b) in enumerate(send_subs)],
                          hops=n - 1)
        else:
            inline = []
            for i, (a, b) in enumerate(send_subs):
                env = held.get(i)
                if env is not None:
                    ctx.forward(right, f"{tag}:ag:{step}:{i}", env)
                else:
                    inline.append((f"{tag}:ag:{step}:{i}", buf[a:b]))
            if inline:
                ctx.send_many(right, inline)
        held = {}
        for i, (a, b) in enumerate(recv_subs):
            part, env = ctx.recv_fwd(left, f"{tag}:ag:{step}:{i}",
                                     op="allreduce")
            if b > a:
                buf[a:b] = part
            if env is not None and int(env.get("hops", 1)) > 1:
                held[i] = env
    return buf


def ring_reducescatter_flat(ctx: GroupContext, buf: np.ndarray,
                            ring_ranks: Sequence[int], tag: str,
                            pipeline_chunks: int = 4) -> np.ndarray:
    """Reduce-scatter half of the ring; returns this rank's reduced chunk."""
    ranks = list(ring_ranks)
    n = len(ranks)
    pos = ranks.index(ctx.rank)
    chunks = _bounds(buf.size, n)
    if n == 1:
        return buf
    right = ranks[(pos + 1) % n]
    left = ranks[(pos - 1) % n]
    for step in range(n - 1):
        send_c = (pos - 1 - step) % n
        recv_c = (pos - 2 - step) % n
        send_subs = _wire_subchunks(ctx, *chunks[send_c], buf.itemsize,
                                    pipeline_chunks)
        recv_subs = _wire_subchunks(ctx, *chunks[recv_c], buf.itemsize,
                                    pipeline_chunks)
        ctx.send_many(right, [(f"{tag}:rs:{step}:{i}", buf[a:b])
                              for i, (a, b) in enumerate(send_subs)])
        for i, (a, b) in enumerate(recv_subs):
            part = ctx.recv(left, f"{tag}:rs:{step}:{i}", op="reducescatter")
            if b > a:
                buf[a:b] += part
    lo, hi = chunks[pos]
    return buf[lo:hi]


def ring_allgather_obj(ctx: GroupContext, value,
                       ring_ranks: Sequence[int], tag: str) -> Dict[int, Any]:
    """Circulate arbitrary per-rank payloads; returns {rank: value}."""
    ranks = list(ring_ranks)
    n = len(ranks)
    pos = ranks.index(ctx.rank)
    out = {ctx.rank: value}
    if n == 1:
        return out
    right = ranks[(pos + 1) % n]
    left = ranks[(pos - 1) % n]
    cur = (ctx.rank, value)
    for step in range(n - 1):
        ctx.send(right, f"{tag}:agx:{step}", cur)
        cur = tuple(ctx.recv(left, f"{tag}:agx:{step}", op="allgather"))
        out[cur[0]] = cur[1]
    return out


def _tree_links(ranks: Sequence[int], root_rank: int, me: int):
    """Binary-tree parent/children of `me` in a tree rooted at root_rank."""
    ranks = list(ranks)
    n = len(ranks)
    root_idx = ranks.index(root_rank)
    v = (ranks.index(me) - root_idx) % n          # virtual index, root=0
    parent = ranks[((v - 1) // 2 + root_idx) % n] if v > 0 else None
    kids = [ranks[(c + root_idx) % n]
            for c in (2 * v + 1, 2 * v + 2) if c < n]
    return v, parent, kids


def tree_broadcast(ctx: GroupContext, value, src_rank: int,
                   ring_ranks: Sequence[int], tag: str):
    """log(N)-depth broadcast from src_rank down a binary tree."""
    v, parent, kids = _tree_links(ring_ranks, src_rank, ctx.rank)
    if parent is not None:
        value = ctx.recv(parent, f"{tag}:bc:{v}", op="broadcast")
    for kid in kids:
        kv, _, _ = _tree_links(ring_ranks, src_rank, kid)
        ctx.send(kid, f"{tag}:bc:{kv}", value)
    return value


def tree_barrier(ctx: GroupContext, ring_ranks: Sequence[int],
                 tag: str) -> None:
    """Tree reduce of arrival tokens + tree broadcast of the release."""
    ranks = list(ring_ranks)
    root = ranks[0]
    v, parent, kids = _tree_links(ranks, root, ctx.rank)
    for kid in kids:
        kv, _, _ = _tree_links(ranks, root, kid)
        ctx.recv(kid, f"{tag}:up:{kv}", op="barrier")
    if parent is not None:
        ctx.send(parent, f"{tag}:up:{v}", True)
    tree_broadcast(ctx, True, root, ranks, tag)


class RingBackend:
    name = "ring"

    def __init__(self, ctx: GroupContext, pipeline_chunks: int = 4):
        self.ctx = ctx
        self.pipeline_chunks = pipeline_chunks
        self._all = list(range(ctx.world))

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        arr = np.asarray(arr)
        seq = self.ctx.next_seq()
        buf = np.ascontiguousarray(arr).ravel().copy()
        ring_allreduce_flat(self.ctx, buf, self._all, f"{seq}:ar",
                            self.pipeline_chunks)
        return buf.reshape(arr.shape)

    def allgather(self, value) -> List[Any]:
        seq = self.ctx.next_seq()
        by_rank = ring_allgather_obj(self.ctx, value, self._all, f"{seq}:ag")
        return [by_rank[r] for r in range(self.ctx.world)]

    def broadcast(self, value, src_rank: int):
        seq = self.ctx.next_seq()
        return tree_broadcast(self.ctx, value, src_rank, self._all,
                              f"{seq}:bc")

    def reducescatter(self, arr: np.ndarray) -> np.ndarray:
        # API layer guarantees arr.shape[0] % world == 0, so the equal
        # flat split below coincides with axis-0 blocks (C-contiguous).
        arr = np.ascontiguousarray(arr)
        seq = self.ctx.next_seq()
        world = self.ctx.world
        buf = arr.ravel().copy()
        chunk = ring_reducescatter_flat(self.ctx, buf, self._all,
                                        f"{seq}:rsc", self.pipeline_chunks)
        out_shape = (arr.shape[0] // world,) + arr.shape[1:]
        return chunk.reshape(out_shape)

    def barrier(self) -> None:
        seq = self.ctx.next_seq()
        tree_barrier(self.ctx, self._all, f"{seq}:bar")
