"""Measured per-op backend cost model (the ``"auto"`` selector's brain).

Replaces the old static world-size thresholds: each backend's round is
priced as ``hops × edge latency + bytes / edge bandwidth`` over the
group's topology edges, using the GCS-folded ``observability/edges``
EWMA model where an edge has warmed up and priors where it hasn't. The
gather funnel is priced from the group's own measured coordinator EWMA
(group.py `_observe_coord`) the same way.

Determinism contract: every rank must dispatch the same backend for the
same op, but edge-stat snapshots differ per rank — so ranks never call
this independently for dispatch. Rank 0 computes the choice and
broadcasts it through the coordinator (api.GroupClient._agree); this
module itself is pure and deterministic in its inputs.

Priors were calibrated against BENCH_collective.json on the 1-vCPU dev
box (the same one the acceptance sweep runs on); they only matter until
the first few rounds warm the EWMAs.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

#: (latency_s, bandwidth_Bps) priors per link class, pre-warmup.
PRIOR_INTRA = (2.0e-3, 400e6)      # same-node mailbox hop / shm pull
PRIOR_INTER = (3.0e-3, 120e6)      # cross-node nodelet pull
PRIOR_COORD_LAT_S = 1.0e-3         # coordinator rendezvous RTT
PRIOR_COORD_BW_BPS = 250e6         # funnel serialization through one proc
#: Fixed per-contribution cost at the coordinator (arg unpack + slot
#: bookkeeping) — what makes gather O(N) even at zero bytes.
MSG_OVERHEAD_S = 2.0e-4
#: Payload stand-in for ops whose payload size is unknowable at selection
#: time (allgather/broadcast of arbitrary objects, barrier tokens).
NOMINAL_PAYLOAD_BYTES = 64 * 1024
#: An edge below this many EWMA observations still uses priors.
MIN_EDGE_OBS = 3

_CANDIDATES = ("gather", "ring", "hier")


def payload_bucket(nbytes: Optional[int]) -> int:
    """log2 size bucket for decision caching (-1 = size-free ops).
    Coarse on purpose: one measured agreement round covers every payload
    within 2x, and all ranks derive the same bucket from the same
    (contract-identical) payload shape."""
    if nbytes is None:
        return -1
    return max(0, int(nbytes).bit_length() - 1)


def _edge_link(edges: Optional[Dict[str, dict]], src: str,
               dst: str) -> Tuple[float, float, bool]:
    """(latency_s, bandwidth_Bps, measured?) for one directed edge,
    falling back to the reverse direction, then to class priors."""
    p_lat, p_bw = PRIOR_INTRA if src == dst else PRIOR_INTER
    for key in (f"{src}->{dst}", f"{dst}->{src}"):
        e = (edges or {}).get(key)
        if not e or e.get("count", 0) < MIN_EDGE_OBS:
            continue
        lat = e.get("latency_ewma_s")
        bw = e.get("bandwidth_ewma_bps")
        # The EWMAs are size-banded (observability/edges.py): an edge
        # that only carried bulk transfers has measured bandwidth but no
        # measured latency (and vice versa) — fall back per-component.
        if (lat and lat > 0) or (bw and bw > 0):
            return (float(lat) if lat and lat > 0 else p_lat,
                    float(bw) if bw and bw > 0 else p_bw, True)
    return p_lat, p_bw, False


def _worst_link(edges, topology, ranks) -> Tuple[float, float, int]:
    """Worst (max latency, min bandwidth) over a ring's consecutive
    edges — a ring round is gated by its slowest hop."""
    if topology is None or not ranks:
        lat, bw = PRIOR_INTRA
        return lat, bw, 0
    worst_lat, worst_bw, measured = 0.0, math.inf, 0
    for i, r in enumerate(ranks):
        src = topology.node_of(r)
        dst = topology.node_of(ranks[(i + 1) % len(ranks)])
        lat, bw, m = _edge_link(edges, src, dst)
        worst_lat = max(worst_lat, lat)
        worst_bw = min(worst_bw, bw)
        measured += int(m)
    return worst_lat, worst_bw, measured


def predict_costs(op: str, world_size: int, topology,
                  payload_bytes: Optional[int] = None, *,
                  edges: Optional[Dict[str, dict]] = None,
                  coord_lat: Optional[float] = None,
                  coord_bw: Optional[float] = None) -> Tuple[Dict[str, float], int]:
    """Predicted seconds per backend for one round of `op`, plus how many
    topology links were priced from measurements (0 = pure priors)."""
    n = max(1, int(world_size))
    p = float(payload_bytes if payload_bytes is not None
              else NOMINAL_PAYLOAD_BYTES)
    c_lat = coord_lat if coord_lat and coord_lat > 0 else PRIOR_COORD_LAT_S
    c_bw = coord_bw if coord_bw and coord_bw > 0 else PRIOR_COORD_BW_BPS
    ranks = list(range(n))
    lat, bw, measured = _worst_link(edges, topology, ranks)
    depth = max(1, math.ceil(math.log2(n))) if n > 1 else 0
    # Co-located ranks share one memory system: a ring step's "parallel"
    # chunk copies all cross the same shm, so the effective bytes moved
    # per step scale with ranks-per-node. This is what lets a funnel
    # (gather/hier) beat the ring inside a node despite moving the same
    # total bytes — it does so in O(1) rounds instead of O(N).
    leaders: list = []
    m_loc = 1
    if topology is not None and n > 1:
        leaders = list(topology.leader_ranks())
        m_loc = max(1, max(len(topology.peers_on_node(rk))
                           for rk in leaders))
        m_loc = min(m_loc, n)

    # --- gather: one rendezvous RTT, funnel serializes world×bytes ------
    base = 2 * c_lat + n * MSG_OVERHEAD_S
    if op in ("allreduce", "reducescatter"):
        g = base + (2 * n * p) / c_bw
    elif op == "allgather":
        g = base + (n * p + n * n * p) / c_bw      # replies carry N×P each
    elif op == "broadcast":
        g = base + (p + n * p) / c_bw
    else:                                          # barrier
        g = base

    # --- ring: 2(N-1) hops of P/N (tree for latency-bound ops);
    # bytes contend m_loc-wide inside a shared-memory domain ------------
    if n == 1:
        r = 0.0
    elif op == "allreduce":
        r = 2 * (n - 1) * (lat + m_loc * (p / n) / bw)
    elif op == "reducescatter":
        r = (n - 1) * (lat + m_loc * (p / n) / bw)
    elif op == "allgather":
        r = (n - 1) * (lat + m_loc * p / bw)
    elif op == "broadcast":
        r = depth * (lat + p / bw)
    else:                                          # tree barrier: up+down
        r = 2 * depth * lat

    # --- hier: intra funnel + leader ring over the slow domain ----------
    if topology is not None and n > 1:
        num_nodes = max(1, len(leaders))
        m = m_loc
        i_lat, i_bw, i_meas = _edge_link(
            edges, topology.node_of(ranks[0]), topology.node_of(ranks[0]))
        x_lat, x_bw, _ = _worst_link(edges, topology, leaders)
        measured = max(measured, i_meas)
        # Per-member rendezvous work at the funnel leader (mailbox
        # put/take handling) does not parallelize across co-located
        # senders — they share the node's cores — so each extra member
        # costs roughly half a measured intra hop on top of its bytes.
        rdv = (m - 1) * i_lat / 2
        if op in ("allreduce", "reducescatter"):
            # members land concurrently in the leader's mailbox: the
            # serial cost is the leader ingesting (m-1)·P (reduce) and
            # emitting it back (broadcast) — 2 rounds, not 2(m-1) hops
            h = 2 * (i_lat + (m - 1) * p / i_bw + rdv + m * MSG_OVERHEAD_S)
            if num_nodes > 1:
                h += 2 * (num_nodes - 1) * (x_lat + (p / num_nodes) / x_bw)
        elif op == "allgather":
            h = (m - 1) * (i_lat + p / i_bw) + rdv
            if num_nodes > 1:
                h += (num_nodes - 1) * (x_lat + m * p / x_bw)
            h += (m - 1) * (i_lat + n * p / i_bw) + rdv
        elif op == "broadcast":
            h = depth * (lat + p / bw)             # same tree as ring
        else:
            h = 2 * depth * lat
    else:
        h = r

    return {"gather": g, "ring": r, "hier": h}, measured


def choose_backend(op: str, world_size: int, topology,
                   payload_bytes: Optional[int] = None, *,
                   edges: Optional[Dict[str, dict]] = None,
                   coord_lat: Optional[float] = None,
                   coord_bw: Optional[float] = None) -> Tuple[str, dict]:
    """(backend name, decision info) — the info dict is what group stats
    and the timeline span args expose."""
    costs, measured = predict_costs(
        op, world_size, topology, payload_bytes,
        edges=edges, coord_lat=coord_lat, coord_bw=coord_bw)
    # stable tie-break: candidate order is fixed, min() keeps the first
    name = min(_CANDIDATES, key=lambda k: costs[k])
    return name, {
        "backend": name,
        "costs_ms": {k: round(v * 1e3, 4) for k, v in costs.items()},
        "payload_bytes": payload_bytes,
        "measured_links": measured,
        "source": "measured" if measured else "priors",
    }
