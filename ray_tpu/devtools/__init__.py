"""Developer tooling that ships with the repo (linters, analyzers).

Nothing under ray_tpu.devtools is imported by the runtime — these are
build/CI-time tools kept in-tree so the gates they enforce evolve with
the code they check.
"""
