"""Deterministic fault-injection plane for the control-plane transport.

The chaos suite we had before this module (tests/test_chaos.py) only
exercises crash-stop failures: a SIGKILLed daemon closes its sockets, so
``ConnectionLost`` fires and recovery kicks in. Real fleets mostly die of
*gray* failures — black-holed links, silently dropped / delayed /
duplicated / reordered messages, slow peers (Huang et al., "Gray
Failure: The Achilles' Heel of Cloud-Scale Systems", HotOS'17). This
module injects exactly those, deterministically:

- A :class:`FaultPlan` is a seed plus an ordered list of
  :class:`FaultRule`\\ s, each matching frames by src/dst process role
  (``driver``/``gcs``/``nodelet``/``worker``, fnmatch patterns), method
  pattern, evaluation side, frame kind, and a time window — mapping
  matches to ``drop`` / ``delay`` / ``duplicate`` / ``reorder`` /
  ``blackhole`` / ``reset`` with probability ``p``.
- The plan rides ``Config.chaos_plan`` (JSON), which every spawned
  daemon and worker inherits through the ``--config`` chain — one plan
  governs the whole cluster. :func:`maybe_install` builds an
  :class:`Interposer` and hands it to ``core.rpc.set_chaos``; the
  transport consults it on its four frame edges (client egress/ingress,
  server ingress/egress — each frame crosses exactly two).
- Determinism: the decision for the *n*-th frame of a given method
  reaching rule *i* is a pure function of
  ``(plan.seed, role, i, method, n)`` — a fresh ``random.Random``
  seeded with that tuple per decision. Keying the stream by method (not
  one stream per rule) matters: wall-clock-driven frames (keepalive
  pings, telemetry reports) interleave nondeterministically with the
  workload's frames, and a shared stream would let a ping steal the
  draw an ``add_job`` got last run. Per-method indices make every
  workload decision identical across same-seed runs regardless of
  interleaving. Every injected fault is appended to a bounded in-memory
  log (:meth:`Interposer.injection_log`); :meth:`Interposer.sequence`
  is its order-independent projection for cross-run comparison.

Side semantics: a rule fires in the process whose edge evaluates it.
``side="send"`` rules run in the frame's sender (src = that process's
role); ``side="recv"`` in the receiver (dst = that process's role);
``side="*"`` in both. Evaluating each direction once per end keeps a
rule's probability from compounding across edges.
"""

from __future__ import annotations

import json
import random
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from fnmatch import fnmatchcase
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import rpc

ACTIONS = ("drop", "delay", "duplicate", "reorder", "blackhole", "reset")
ROLES = ("driver", "gcs", "nodelet", "worker")

_KIND_NAMES = {rpc.REQUEST: "request", rpc.RESPONSE_OK: "response",
               rpc.RESPONSE_ERR: "response", rpc.ONEWAY: "oneway",
               rpc.PING: "ping", rpc.PONG: "ping"}


@dataclass
class Verdict:
    action: str = "pass"      # pass | drop | delay | duplicate | reset
    delay_s: float = 0.0
    rule: int = -1            # index of the firing rule (-1: none)


_PASS = Verdict()


@dataclass
class FaultRule:
    """One match→action rule. All string fields are fnmatch patterns."""
    src: str = "*"            # sender role
    dst: str = "*"            # receiver role
    method: str = "*"         # rpc method ("__ping__" for keepalive pings)
    side: str = "send"        # evaluation edge: "send" | "recv" | "*"
    action: str = "drop"
    p: float = 1.0            # firing probability per matching frame
    delay_s: float = 0.05     # delay action: fixed; reorder: uniform(0, x)
    after_s: float = 0.0      # window start, relative to interposer install
    for_s: float = -1.0       # window length (-1: unbounded)
    blackhole_s: float = 1.0  # how long a triggered black hole lasts
    max_count: int = -1       # firings before the rule retires (-1: none)
    kinds: Tuple[str, ...] = ("request", "oneway", "response", "ping")

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown chaos action: {self.action!r}")
        if self.side not in ("send", "recv", "*"):
            raise ValueError(f"unknown chaos side: {self.side!r}")
        self.kinds = tuple(self.kinds)


@dataclass
class FaultPlan:
    """Seed + ordered rules; JSON round-trips through Config.chaos_plan."""
    seed: int = 0
    rules: List[FaultRule] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "rules": [asdict(r) for r in self.rules]})

    @classmethod
    def from_json(cls, s: str) -> "FaultPlan":
        d = json.loads(s)
        return cls(seed=int(d.get("seed", 0)),
                   rules=[FaultRule(**r) for r in d.get("rules", [])])


class Interposer:
    """Per-process fault decider installed into core.rpc.

    Thread-safety: decisions run on the owning process's event loop
    thread (the transport's frame edges), so no lock is taken; the
    injection log is a plain deque read by tests after quiescence.
    """

    def __init__(self, plan: FaultPlan, role: str):
        self.plan = plan
        self.role = role
        self._t0 = time.monotonic()
        # Per-(rule, method) frame indices: the n-th METHOD frame that
        # rule i evaluates decides via a Random seeded with
        # (seed, role, i, method, n) — a pure function, so workload
        # frames decide identically across runs no matter how pings or
        # telemetry interleave with them (see module docstring). First
        # firing rule wins; earlier matching-but-not-firing rules still
        # consume their index, later rules consume nothing.
        self._method_draws: Dict[Tuple[int, str], int] = {}
        self._fired = [0] * len(plan.rules)
        self._draws = [0] * len(plan.rules)
        # (side, peer_key) -> monotonic expiry; while active, EVERY frame
        # on that edge/peer drops (the link is dark, not one method)
        self._blackholes: Dict[Tuple[str, Any], float] = {}
        self._peer_roles: Dict[Tuple[str, int], str] = {}
        self.log: deque = deque(maxlen=8192)

    # -- wiring ----------------------------------------------------------
    def note_peer(self, addr: Tuple[str, int], role: str) -> None:
        """Teach the interposer a server address's role (dst matching on
        the send side; src matching on the client's response ingress)."""
        self._peer_roles[tuple(addr)] = role

    def peer_role(self, addr: Optional[Tuple[str, int]]) -> str:
        if addr is None:
            return "*"
        return self._peer_roles.get(tuple(addr), "*")

    # -- decision --------------------------------------------------------
    def on_frame(self, side: str, method: str, kind: int,
                 peer: Optional[Tuple[str, int]] = None,
                 peer_role: Optional[str] = None) -> Verdict:
        """Decide the fate of one frame crossing one transport edge."""
        if peer_role is None:
            peer_role = self.peer_role(peer)
        if side == "send":
            src, dst = self.role, peer_role
        else:
            src, dst = peer_role, self.role
        now = time.monotonic()
        key = (side, tuple(peer) if peer is not None else peer_role)
        until = self._blackholes.get(key)
        if until is not None:
            if now < until:
                return Verdict("drop", rule=-1)
            del self._blackholes[key]
        kname = _KIND_NAMES.get(kind, "request")
        rel = now - self._t0
        for i, rule in enumerate(self.plan.rules):
            if rule.side != "*" and rule.side != side:
                continue
            if kname not in rule.kinds:
                continue
            if rel < rule.after_s:
                continue
            if rule.for_s >= 0 and rel >= rule.after_s + rule.for_s:
                continue
            if rule.max_count >= 0 and self._fired[i] >= rule.max_count:
                continue
            if not (fnmatchcase(src, rule.src)
                    and fnmatchcase(dst, rule.dst)
                    and fnmatchcase(method, rule.method)):
                continue
            mk = (i, method)
            n = self._method_draws.get(mk, 0) + 1
            self._method_draws[mk] = n
            self._draws[i] += 1
            rng = random.Random(f"{self.plan.seed}:{self.role}:{i}:{method}:{n}")
            if rule.p < 1.0 and rng.random() >= rule.p:
                continue
            self._fired[i] += 1
            action, delay = rule.action, rule.delay_s
            if action == "reorder":
                # a sampled delay lets later frames overtake this one
                action, delay = "delay", rng.uniform(0.0, rule.delay_s)
            elif action == "blackhole":
                self._blackholes[key] = now + rule.blackhole_s
                action = "drop"
            self.log.append({"n": n, "rule": i,
                             "t": round(rel, 4), "side": side, "src": src,
                             "dst": dst, "method": method, "kind": kname,
                             "action": rule.action})
            return Verdict(action, delay, i)
        return _PASS

    # -- introspection ---------------------------------------------------
    def injection_log(self) -> List[dict]:
        return list(self.log)

    # methods whose frame COUNT is wall-clock-driven (periodic loops),
    # so they're excluded from cross-run sequence comparison by default
    TIMER_METHODS = ("__ping__", "telemetry_report", "heartbeat")

    def sequence(self, ignore_methods: Tuple[str, ...] = TIMER_METHODS
                 ) -> List[Tuple[int, str, int, str, str]]:
        """The determinism-comparable projection of the log: per-(rule,
        method) frame index + action, no wall-clock, sorted so that the
        nondeterministic *interleaving* of independent method streams
        doesn't matter. Wall-clock-driven methods (pings by default) are
        excluded — their frame COUNT is timing-dependent even though
        each decision is deterministic."""
        return sorted((e["rule"], e["method"], e["n"], e["side"], e["action"])
                      for e in self.log if e["method"] not in ignore_methods)

    def stats(self) -> dict:
        return {"role": self.role, "seed": self.plan.seed,
                "fired": list(self._fired), "draws": list(self._draws),
                "active_blackholes": sum(
                    1 for t in self._blackholes.values()
                    if t > time.monotonic())}


def maybe_install(cfg, role: str) -> Optional[Interposer]:
    """Install the session FaultPlan (if any) into this process's
    transport. Called from every process entrypoint; idempotent per
    process — a second call with the same plan JSON reuses the installed
    interposer so runtime + worker init in one process share streams."""
    plan_json = getattr(cfg, "chaos_plan", "") or ""
    if not plan_json:
        return None
    cur = rpc.get_chaos()
    if cur is not None and getattr(cur, "_plan_json", None) == plan_json \
            and cur.role == role:
        return cur
    ip = Interposer(FaultPlan.from_json(plan_json), role)
    ip._plan_json = plan_json
    rpc.set_chaos(ip)
    return ip


def note_peer(addr, role: str) -> None:
    """Register a server address's role with the installed interposer
    (no-op when chaos is off — safe to call unconditionally)."""
    ip = rpc.get_chaos()
    if ip is not None:
        ip.note_peer(tuple(addr), role)


def uninstall() -> None:
    rpc.set_chaos(None)


# --------------------------------------------------------------------------
# Scenario running (chaos pytest fixture + `cli chaos`)
# --------------------------------------------------------------------------

def canonical_plan(seed: int = 0) -> FaultPlan:
    """The acceptance-criteria mix: drop/delay/duplicate/black-hole on
    control-plane links, duplication aimed at the non-idempotent RPCs
    the dedupe layer protects."""
    return FaultPlan(seed=seed, rules=[
        # gray latency + reordering on everything the driver sends
        FaultRule(src="driver", dst="*", side="send", action="reorder",
                  p=0.15, delay_s=0.05),
        # lossy driver->control-plane requests (retry/deadline pressure)
        FaultRule(src="driver", dst="gcs", side="send", action="drop",
                  p=0.1, kinds=("request",)),
        # duplicated delivery of the classic non-idempotent RPCs,
        # evaluated at the receiving daemon
        FaultRule(src="*", dst="*", method="create_actor", side="recv",
                  action="duplicate", p=0.5, kinds=("request",)),
        FaultRule(src="*", dst="*", method="request_lease", side="recv",
                  action="duplicate", p=0.3, kinds=("request",)),
        FaultRule(src="*", dst="*", method="pin_object*", side="recv",
                  action="duplicate", p=0.5, kinds=("request",)),
        FaultRule(src="*", dst="*", method="report_gang_demand",
                  side="recv", action="duplicate", p=0.5,
                  kinds=("request",)),
        # one 1.5s black hole of the driver->gcs link mid-run: keepalive
        # must convert it to ConnectionLost and gcs_call must ride it out
        FaultRule(src="driver", dst="gcs", side="send", action="blackhole",
                  p=1.0, after_s=3.0, max_count=1, blackhole_s=1.5),
    ])


# system_config every scenario runs under: tight deadlines so injected
# loss surfaces (and bounds) fast, keepalive quick enough to catch the
# black hole inside the test budget
SCENARIO_CONFIG = {
    "rpc_call_timeout_s": 5.0,
    "rpc_keepalive_interval_s": 0.25,
    "rpc_keepalive_timeout_s": 1.5,
    "gcs_reconnect_timeout_s": 20.0,
    "health_check_period_s": 0.2,
}


def run_scenario(plan: Optional[FaultPlan] = None, *, seed: int = 0,
                 num_nodes: int = 1, tasks: int = 8, actors: int = 2,
                 calls: int = 4,
                 system_config: Optional[dict] = None) -> dict:
    """Run the canonical task+actor workload under a FaultPlan and check
    the three scenario invariants:

    1. every operation completes or fails *typed* within its deadline
       bound (no silent hang past rpc_call_timeout_s +
       rpc_keepalive_timeout_s, with retry slack);
    2. no duplicate side effects (every actor saw exactly its own calls;
       post-workload node resources return to their totals — a
       double-created actor or double-granted lease would leak);
    3. no orphaned pins (state.memory_report leak_suspects stays empty
       after every ref is dropped).

    Returns a report dict; report["ok"] is the scenario verdict."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    plan = plan if plan is not None else canonical_plan(seed)
    sc = dict(SCENARIO_CONFIG)
    if system_config:
        sc.update(system_config)
    sc["chaos_plan"] = plan.to_json()
    bound = (sc["rpc_call_timeout_s"] + sc["rpc_keepalive_timeout_s"])
    # per-op budget: deadline bound x retry allowance (task retries and
    # gcs reconnect both legitimately chain a few bounded attempts)
    op_budget = bound * 6
    violations: List[str] = []
    t_start = time.monotonic()
    cluster = Cluster(initialize_head=False, system_config=sc)
    for _ in range(max(1, num_nodes)):
        cluster.add_node(resources={"CPU": 4.0})
    report: Dict[str, Any] = {"seed": plan.seed, "rules": len(plan.rules)}
    try:
        cluster.connect(_system_config=sc)

        def timed(label, fn):
            t0 = time.monotonic()
            try:
                return fn()
            except Exception as e:
                if not isinstance(e, (rpc.RpcError,
                                      ray_tpu.exceptions.RayTpuError,
                                      TimeoutError)):
                    violations.append(
                        f"{label}: untyped failure {type(e).__name__}: {e}")
                return None
            finally:
                el = time.monotonic() - t0
                if el > op_budget:
                    violations.append(
                        f"{label}: took {el:.1f}s > {op_budget:.1f}s bound")

        @ray_tpu.remote(max_retries=5)
        def _square(x):
            return x * x

        @ray_tpu.remote(max_restarts=0)
        class _Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

            def total(self):
                return self.n

        # tasks + puts
        refs = [_square.remote(i) for i in range(tasks)]
        vals = timed("tasks", lambda: ray_tpu.get(refs, timeout=op_budget))
        if vals is not None and vals != [i * i for i in range(tasks)]:
            violations.append(f"tasks: wrong results {vals}")
        put_refs = [ray_tpu.put(bytes(1024) + bytes([i])) for i in range(4)]
        timed("puts", lambda: ray_tpu.get(put_refs, timeout=op_budget))

        # actors: exactly-once side effects under duplicated create/call
        handles = [timed(f"actor{i}", _Counter.remote) for i in range(actors)]
        handles = [h for h in handles if h is not None]
        for i, h in enumerate(handles):
            for _ in range(calls):
                timed(f"bump{i}", lambda h=h: ray_tpu.get(
                    h.bump.remote(), timeout=op_budget))
            n = timed(f"total{i}", lambda h=h: ray_tpu.get(
                h.total.remote(), timeout=op_budget))
            if n is not None and n != calls:
                violations.append(
                    f"actor{i}: {n} side effects for {calls} calls "
                    "(duplicate or lost execution)")
        for h in handles:
            try:
                ray_tpu.kill(h)
            except Exception:
                pass
        del refs, put_refs, vals, handles

        # settle, then audit pins + resource accounting
        time.sleep(max(1.0, sc["health_check_period_s"] * 5))
        from ray_tpu.util import state as _state
        mem = timed("memory_report", _state.memory_report)
        if mem:
            leaks = mem.get("leak_suspects") or []
            if leaks:
                violations.append(f"orphaned pins: {leaks[:5]}")

        def _accounting():
            # leases return lazily (lease_reuse_grace_s + chaos-delayed
            # return_lease frames): poll up to the op budget
            deadline = time.monotonic() + op_budget
            while True:
                tot = ray_tpu.cluster_resources()
                avail = ray_tpu.available_resources()
                missing = {k: (avail.get(k, 0.0), v) for k, v in tot.items()
                           if abs(avail.get(k, 0.0) - v) > 1e-6}
                if not missing or time.monotonic() > deadline:
                    return missing
                time.sleep(0.25)

        missing = timed("accounting", _accounting)
        if missing:
            violations.append(
                f"resources not returned after workload (leaked "
                f"lease/lane or duplicate grant): {missing}")
        ip = rpc.get_chaos()
        report["injected_driver_side"] = len(ip.log) if ip else 0
        report["sequence"] = ip.sequence() if ip else []
    finally:
        try:
            cluster.shutdown()
        finally:
            uninstall()
            # the driver runtime rebound module transport defaults to the
            # tight scenario values; restore stock defaults so later
            # in-process users (the rest of a pytest session) aren't
            # running with a 5s deadline and 0.25s keepalive
            from ray_tpu.core.config import Config
            rpc.configure(Config())
    report["elapsed_s"] = round(time.monotonic() - t_start, 2)
    report["violations"] = violations
    report["ok"] = not violations
    return report
