"""Finding: one diagnostic emitted by a raylint rule."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Finding:
    rule: str           # rule id, e.g. "leaked-object-ref"
    path: str           # file the finding is in (as given on the cmdline)
    line: int           # 1-based
    col: int            # 0-based, ast convention
    message: str        # what is wrong at this site
    hint: str = ""      # how to fix it (one line)
    suppressed: bool = field(default=False)

    def render(self) -> str:
        tail = f"  [hint: {self.hint}]" if self.hint else ""
        sup = "  (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}{tail}{sup}")

    def to_dict(self) -> dict:
        # Stable --json schema; tests/test_lint.py pins these keys.
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "suppressed": self.suppressed,
        }
