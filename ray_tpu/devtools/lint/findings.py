"""Finding: one diagnostic emitted by a raylint rule."""

from __future__ import annotations

from dataclasses import dataclass, field

SCHEMA_VERSION = 3
SEVERITIES = ("error", "warn")


@dataclass
class Finding:
    rule: str           # rule id, e.g. "leaked-object-ref"
    path: str           # file the finding is in (as given on the cmdline)
    line: int           # 1-based
    col: int            # 0-based, ast convention
    message: str        # what is wrong at this site
    hint: str = ""      # how to fix it (one line)
    severity: str = "error"   # "error" | "warn"
    suppressed: bool = field(default=False)
    # SPMD facts backing the finding (schema v3): e.g. the declared-axes
    # set for mesh-axis-consistency, the per-arm schedule diff for
    # collective-schedule-divergence. {} for rules with nothing to add.
    spmd: dict = field(default_factory=dict)

    def render(self) -> str:
        tail = f"  [hint: {self.hint}]" if self.hint else ""
        sup = "  (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.severity}: {self.rule}: "
                f"{self.message}{tail}{sup}")

    def to_dict(self) -> dict:
        # Stable --json schema v3; tests/test_lint.py pins these keys.
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "severity": self.severity,
            "suppressed": self.suppressed,
            "spmd": self.spmd,
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "Finding":
        """Accepts v1 dicts (no severity field — everything was an
        error), v2 (no spmd facts), and v3; tooling reading old CI
        artifacts keeps working."""
        return cls(
            rule=doc["rule"], path=doc["path"], line=doc["line"],
            col=doc["col"], message=doc["message"],
            hint=doc.get("hint", ""),
            severity=doc.get("severity", "error"),
            suppressed=doc.get("suppressed", False),
            spmd=doc.get("spmd", {}))
