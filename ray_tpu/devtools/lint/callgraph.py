"""Project call graph over per-file summaries.

Nodes are functions, identified as ``"<module>:<qualname>"`` (e.g.
``ray_tpu.serve.controller:ServeController._stop``). Edges come from
call-site name resolution — flow-insensitive and deliberately partial:
a callee the resolver cannot pin to exactly one project function is
dropped, so interprocedural rules under-approximate reachability
instead of spraying false positives through the tier-1 gate.

Resolution handles the shapes this codebase actually uses:

- bare names -> same-module functions, then ``from x import f`` imports
- ``self.m`` / ``cls.m`` -> the enclosing class, then its bases
  (project-wide, matched by class name)
- ``C.m`` / ``mod.f`` -> classes/modules visible through the import map

Reachability is depth-capped (``depth``): summaries propagate at most
that many call hops, which bounds both analysis cost and the blast
radius of a resolution mistake.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ray_tpu.devtools.lint.summaries import (ClassSummary, FileSummary,
                                             FunctionSummary)

DEFAULT_DEPTH = 6


class ProjectGraph:
    """Whole-program view handed to ``scope = "graph"`` rules."""

    def __init__(self, files: List[FileSummary],
                 depth: int = DEFAULT_DEPTH):
        self.files = files
        self.depth = depth
        self.functions: Dict[str, FunctionSummary] = {}
        self.fn_path: Dict[str, str] = {}           # node id -> file path
        self.classes: Dict[str, Tuple[str, ClassSummary]] = {}
        self.class_index: Dict[str, List[Tuple[str, ClassSummary]]] = {}
        self.actor_methods: Dict[str, List[str]] = {}  # meth -> [cls names]
        self._by_module: Dict[str, Dict[str, str]] = {}
        self._imports: Dict[str, Dict[str, str]] = {}
        self._resolve_cache: Dict[Tuple[str, str, str], Optional[str]] = {}

        for fs in files:
            mod_fns = self._by_module.setdefault(fs.module, {})
            self._imports[fs.module] = fs.imports
            for f in fs.functions:
                nid = f"{fs.module}:{f.qualname}"
                self.functions[nid] = f
                self.fn_path[nid] = fs.path
                mod_fns.setdefault(f.qualname, nid)
            for c in fs.classes:
                self.classes.setdefault(c.name, (fs.module, c))
                self.class_index.setdefault(c.name, []).append(
                    (fs.module, c))
                if c.is_actor:
                    for m in c.methods:
                        self.actor_methods.setdefault(m, [])
                        if c.name not in self.actor_methods[m]:
                            self.actor_methods[m].append(c.name)

    # -- identity helpers ------------------------------------------------
    def node_id(self, module: str, qualname: str) -> str:
        return f"{module}:{qualname}"

    def summary(self, nid: str) -> Optional[FunctionSummary]:
        return self.functions.get(nid)

    def class_of(self, name: str, prefer_module: str = ""
                 ) -> Optional[Tuple[str, ClassSummary]]:
        hits = self.class_index.get(name, [])
        for mod, cs in hits:
            if mod == prefer_module:
                return mod, cs
        return hits[0] if hits else None

    def method_node(self, cls_name: str, method: str,
                    prefer_module: str = "") -> Optional[str]:
        """Resolve Class.method to a node id, walking base classes."""
        seen = set()
        queue = deque([cls_name])
        while queue:
            cname = queue.popleft()
            if cname in seen:
                continue
            seen.add(cname)
            hit = self.class_of(cname, prefer_module)
            if hit is None:
                continue
            mod, cs = hit
            if method in cs.methods:
                nid = self.node_id(mod, f"{cs.name}.{method}")
                if nid in self.functions:
                    return nid
            queue.extend(cs.bases)
        return None

    def attr_type(self, cls_name: str, attr: str,
                  prefer_module: str = "") -> Tuple[str, str, str]:
        """(tag, defining_module, defining_class) for self.<attr>, walking
        bases; ('', '', '') when unknown."""
        seen = set()
        queue = deque([cls_name])
        while queue:
            cname = queue.popleft()
            if cname in seen:
                continue
            seen.add(cname)
            hit = self.class_of(cname, prefer_module)
            if hit is None:
                continue
            mod, cs = hit
            if attr in cs.attr_types:
                return cs.attr_types[attr], mod, cs.name
            queue.extend(cs.bases)
        return "", "", ""

    # -- call resolution -------------------------------------------------
    def resolve_call(self, module: str, cls: str, name: str
                     ) -> Optional[str]:
        """Node id for a call-site name seen in (module, class) context,
        or None when it cannot be pinned to one project function."""
        key = (module, cls, name)
        if key in self._resolve_cache:
            return self._resolve_cache[key]
        nid = self._resolve_uncached(module, cls, name)
        self._resolve_cache[key] = nid
        return nid

    def _resolve_uncached(self, module: str, cls: str, name: str
                          ) -> Optional[str]:
        parts = name.split(".")
        mod_fns = self._by_module.get(module, {})
        imports = self._imports.get(module, {})

        if parts[0] in ("self", "cls") and len(parts) == 2 and cls:
            return self.method_node(cls, parts[1], prefer_module=module)
        if len(parts) == 1:
            n = parts[0]
            if n in mod_fns:
                return mod_fns[n]
            target = imports.get(n)
            if target and "." in target:
                tmod, tfn = target.rsplit(".", 1)
                hit = self._by_module.get(tmod, {}).get(tfn)
                if hit:
                    return hit
                # `from pkg import Class` then Class(...) — constructor
                pair = self.class_of(tfn, prefer_module=tmod)
                if pair and pair[0] == tmod:
                    return self.method_node(tfn, "__init__", tmod)
            # nested function: unique `outer.<n>` in this module
            nested = [nid for qn, nid in mod_fns.items()
                      if qn.endswith(f".{n}")]
            if len(nested) == 1:
                return nested[0]
            return None
        if len(parts) == 2:
            root, leaf = parts
            # Class.method in this module or through imports
            if root[:1].isupper():
                pair = self.class_of(root, prefer_module=module)
                target = imports.get(root)
                if target and "." in target:
                    tmod, tcls = target.rsplit(".", 1)
                    pair = self.class_of(tcls, prefer_module=tmod) or pair
                if pair:
                    return self.method_node(pair[1].name, leaf, pair[0])
                return None
            # mod.f through `import mod` / `from pkg import mod`
            target = imports.get(root)
            if target:
                hit = self._by_module.get(target, {}).get(leaf)
                if hit:
                    return hit
            if root in self._by_module:
                return self._by_module[root].get(leaf)
        return None

    def successors(self, nid: str) -> Iterator[Tuple[str, List]]:
        """(callee node id, call site [name, line, col]) pairs."""
        s = self.functions.get(nid)
        if s is None:
            return
        module = nid.split(":", 1)[0]
        for site in s.calls:
            callee = self.resolve_call(module, s.cls, site[0])
            if callee is not None and callee != nid:
                yield callee, site

    # -- reachability ----------------------------------------------------
    def reach(self, start: str, depth: Optional[int] = None,
              include_start: bool = True
              ) -> Iterator[Tuple[str, List[List]]]:
        """BFS over call edges from ``start`` up to the depth cap,
        yielding (node id, call-site path from start). The path is the
        chain of [name, line, col] sites that led there."""
        cap = self.depth if depth is None else depth
        seen = {start}
        queue: deque = deque([(start, [], 0)])
        while queue:
            nid, path, d = queue.popleft()
            if include_start or nid != start:
                yield nid, path
            if d >= cap:
                continue
            for callee, site in self.successors(nid):
                if callee not in seen:
                    seen.add(callee)
                    queue.append((callee, path + [site], d + 1))

    def find(self, start: str,
             pred: Callable[[FunctionSummary], bool],
             depth: Optional[int] = None
             ) -> Optional[Tuple[str, List[List]]]:
        """First reachable node whose summary satisfies ``pred``."""
        for nid, path in self.reach(start, depth):
            s = self.functions.get(nid)
            if s is not None and pred(s):
                return nid, path
        return None

    # -- domain-specific lookups ----------------------------------------
    def collectives_reachable(self, start: str,
                              depth: Optional[int] = None
                              ) -> Dict[str, Tuple[str, List[List], List]]:
        """{op: (node id, call path, op site)} over the reachable set."""
        out: Dict[str, Tuple[str, List[List], List]] = {}
        for nid, path in self.reach(start, depth):
            s = self.functions.get(nid)
            if s is None:
                continue
            for op, line, col in s.collectives:
                out.setdefault(op, (nid, path, [op, line, col]))
        return out

    def declared_axes(self) -> Dict[str, Tuple[str, int]]:
        """{axis name: (declaring path, line)} over every file's SPMD
        extract — module constants (AXIS_ORDER = (...)) plus in-function
        mesh constructions (Mesh/make_mesh/MeshSpec/DCNSpec)."""
        out: Dict[str, Tuple[str, int]] = {}
        for fs in self.files:
            for ax, line in (fs.spmd or {}).get("axis_decls", []):
                out.setdefault(ax, (fs.path, line))
            for f in fs.functions:
                for ax, line in (f.spmd or {}).get("axis_decls", []):
                    out.setdefault(ax, (fs.path, line))
        return out

    def linearize_events(self, module: str, cls: str, events: List[List],
                         depth: Optional[int] = None,
                         _seen: frozenset = frozenset()
                         ) -> List[Tuple[str, str]]:
        """Flatten an ordered SPMD event list into (op, axis-or-group)
        tokens, inlining resolvable helper calls depth-first so the
        result is the rank's actual rendezvous order. Depth-capped and
        cycle-safe; unresolvable calls contribute nothing (conservative:
        under-approximates, never invents an op)."""
        cap = self.depth if depth is None else depth
        out: List[Tuple[str, str]] = []
        for ev in events:
            if ev[0] == "op":
                out.append((ev[1], ev[2]))
                continue
            callee = self.resolve_call(module, cls, ev[1])
            if callee is None or callee in _seen or cap <= 0:
                continue
            cs = self.functions.get(callee)
            if cs is None:
                continue
            out.extend(self.linearize_events(
                callee.split(":", 1)[0], cs.cls,
                (cs.spmd or {}).get("schedule", []),
                cap - 1, _seen | {callee}))
        return out

    def resolve_lock(self, module: str, cls: str, expr: str
                     ) -> Tuple[str, str]:
        """(lock key, kind) for an acquisition expression, ('', '') when
        unknown. Keys name the defining site: 'module:Class.attr' or
        'module:NAME'."""
        parts = expr.split(".")
        if parts[0] == "self" and len(parts) == 2 and cls:
            tag, dmod, dcls = self.attr_type(cls, parts[1],
                                             prefer_module=module)
            if tag in ("lock", "rlock", "cond"):
                return f"{dmod}:{dcls}.{parts[1]}", tag
            return "", ""
        if len(parts) == 1:
            for fs in self.files:
                if fs.module == module:
                    tag = fs.module_types.get(parts[0], "")
                    if tag in ("lock", "rlock", "cond"):
                        return f"{module}:{parts[0]}", tag
                    target = fs.imports.get(parts[0])
                    if target and "." in target:
                        tmod, tname = target.rsplit(".", 1)
                        for other in self.files:
                            if other.module == tmod:
                                tag = other.module_types.get(tname, "")
                                if tag in ("lock", "rlock", "cond"):
                                    return f"{tmod}:{tname}", tag
                    break
            return "", ""
        return "", ""


def build_graph(files: List[FileSummary],
                depth: int = DEFAULT_DEPTH) -> ProjectGraph:
    return ProjectGraph(files, depth=depth)
