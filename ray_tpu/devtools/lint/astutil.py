"""Shared AST helpers for raylint rules."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)
ScopeNode = FuncNode + (ast.Lambda,)


def dotted_name(func: ast.AST) -> str:
    """``a.b.c`` for an Attribute chain rooted at a Name; chains rooted
    at a call/subscript/other expression get a ``?`` root (so callers can
    still match on the tail): ``foo().bar.remote`` -> ``?.bar.remote``."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def walk_scope(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk ``fn``'s body without descending into nested function/lambda
    scopes (their statements belong to the inner scope)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ScopeNode):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def is_generator(fn: ast.AST) -> bool:
    """True if ``fn`` is a generator function (own-scope yield)."""
    if not isinstance(fn, FuncNode):
        return False
    return any(isinstance(n, (ast.Yield, ast.YieldFrom))
               for n in walk_scope(fn))


def functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, FuncNode):
            yield node


def exception_names(handler: ast.ExceptHandler) -> List[str]:
    """Names an ``except`` clause catches; [] for a bare except."""
    t = handler.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
    return out


def catches(handler: ast.ExceptHandler, exc: str) -> bool:
    names = exception_names(handler)
    return not names or exc in names or "BaseException" in names \
        or (exc != "BaseException" and "Exception" in names)


def enclosing_stack(tree: ast.AST, target: ast.AST) -> List[ast.AST]:
    """Ancestor chain (outermost first) of ``target`` within ``tree``;
    [] if not found. O(tree) — fine for lint-sized files."""
    path: List[ast.AST] = []

    def visit(node: ast.AST, trail: List[ast.AST]) -> bool:
        if node is target:
            path.extend(trail)
            return True
        for child in ast.iter_child_nodes(node):
            if visit(child, trail + [node]):
                return True
        return False

    visit(tree, [])
    return path


def decorator_names(fn: ast.AST) -> List[str]:
    """Dotted names of each decorator, unwrapping calls:
    ``@ray_tpu.remote(num_cpus=1)`` -> ``ray_tpu.remote``."""
    out = []
    for dec in getattr(fn, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        out.append(dotted_name(node))
    return out


def is_remote_decorated(fn: ast.AST) -> bool:
    return any(d == "remote" or d.endswith(".remote")
               for d in decorator_names(fn))
