"""raylint engine: file discovery, parsing, rule dispatch.

Degrades gracefully: a file that fails to parse yields a single
``syntax-error`` finding (it still fails the gate — broken source in
the tree is a finding, not a crash) and generated/bytecode trees
(``__pycache__``, ``*_pb2*.py``, ``protobuf/`` output) are skipped.
"""

from __future__ import annotations

import ast
import os
import subprocess
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, all_rules
from ray_tpu.devtools.lint.suppress import Suppressions

SKIP_DIRS = {"__pycache__", ".git", "build", "node_modules", ".eggs"}
# generated trees: protobuf output and anything stamped *_pb2
_GENERATED_MARKERS = ("_pb2.py", "_pb2_grpc.py")


@dataclass
class ParsedFile:
    path: str
    source: str
    tree: ast.Module
    suppressions: Suppressions


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    files_skipped: int = 0
    parse_errors: int = 0

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.unsuppressed:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def summary_line(self) -> str:
        # bench.py-style single greppable line for CI diffing
        return (f"RAYLINT files={self.files_scanned} "
                f"findings={len(self.unsuppressed)} "
                f"suppressed={len(self.suppressed)} "
                f"parse_errors={self.parse_errors}")

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "summary": {
                "files_scanned": self.files_scanned,
                "files_skipped": self.files_skipped,
                "parse_errors": self.parse_errors,
                "findings": len(self.unsuppressed),
                "suppressed": len(self.suppressed),
                "by_rule": self.by_rule(),
            },
            "findings": [f.to_dict() for f in self.findings],
        }


def _is_generated(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    if any(norm.endswith(m) for m in _GENERATED_MARKERS):
        return True
    # protobuf output dir: skip generated modules, keep the generator
    parts = norm.split("/")
    if "protobuf" in parts[:-1]:
        return parts[-1] not in ("gen.py", "__init__.py")
    return False


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for name in sorted(names):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return [f for f in dict.fromkeys(out) if not _is_generated(f)]


def changed_files(repo_root: str = ".") -> Optional[List[str]]:
    """Paths changed vs HEAD plus untracked files, or None if git is
    unavailable (caller falls back to a full scan)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"], cwd=repo_root,
            capture_output=True, text=True, timeout=30, check=True)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=repo_root, capture_output=True, text=True, timeout=30,
            check=True)
    except (OSError, subprocess.SubprocessError):
        return None
    names = diff.stdout.split() + untracked.stdout.split()
    return [os.path.join(repo_root, n) if repo_root != "." else n
            for n in names if n.endswith(".py")]


def run_lint(paths: Sequence[str],
             rules: Optional[Iterable[Rule]] = None,
             changed_only: bool = False) -> LintReport:
    report = LintReport()
    files = collect_files(paths)
    if changed_only:
        changed = changed_files()
        if changed is not None:
            allowed = {os.path.abspath(c) for c in changed}
            files = [f for f in files if os.path.abspath(f) in allowed]

    parsed_files: List[ParsedFile] = []
    for path in files:
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            report.parse_errors += 1
            report.findings.append(Finding(
                rule="syntax-error", path=path,
                line=e.lineno or 1, col=e.offset or 0,
                message=f"file does not parse: {e.msg}",
                hint="raylint skipped this file's rules; fix the syntax"))
            continue
        except OSError as e:
            report.files_skipped += 1
            report.findings.append(Finding(
                rule="syntax-error", path=path, line=1, col=0,
                message=f"file unreadable: {e}"))
            continue
        parsed_files.append(
            ParsedFile(path, source, tree, Suppressions(source)))

    report.files_scanned = len(parsed_files)
    active = list(rules) if rules is not None else all_rules()

    raw: List[Finding] = []
    for rule in active:
        if rule.scope == "project":
            raw.extend(rule.check_project(parsed_files))
        else:
            for pf in parsed_files:
                raw.extend(rule.check(pf))

    supp_by_path = {pf.path: pf.suppressions for pf in parsed_files}
    for f in raw:
        supp = supp_by_path.get(f.path)
        if supp is not None and supp.is_suppressed(f.rule, f.line):
            f.suppressed = True
    report.findings.extend(raw)
    report.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return report
