"""raylint engine: file discovery, parsing, rule dispatch, result cache.

Degrades gracefully: a file that fails to parse yields a single
``syntax-error`` finding (it still fails the gate — broken source in
the tree is a finding, not a crash) and generated/bytecode trees
(``__pycache__``, ``*_pb2*.py``, ``protobuf/`` output) are skipped.

Phases per run:

1. per-file: parse + ``scope="file"`` rules + summary extraction
   (summaries.py). This whole phase is served from the result cache
   on a hit — keyed by (content sha256, ruleset fingerprint) — so a
   warm run over an unchanged tree does no parsing and no rule work.
2. graph: the :class:`ProjectGraph` is built once from the summaries
   and every ``scope="graph"`` rule runs against it (interprocedural
   deadlock/lock-order/channel-protocol analyses live here).
3. report: ``scope="report"`` meta-rules see the raw findings (the
   useless-suppression audit).

The ruleset fingerprint hashes the analyzer's own source (engine,
summaries, call graph, every active rule), so editing any rule — not
just bumping RULESET_VERSION — invalidates the cache honestly.
"""

from __future__ import annotations

import ast
import hashlib
import inspect
import json
import os
import subprocess
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ray_tpu.devtools.lint.findings import SCHEMA_VERSION, Finding
from ray_tpu.devtools.lint.registry import Rule, all_rules
from ray_tpu.devtools.lint.suppress import Suppressions

SKIP_DIRS = {"__pycache__", ".git", "build", "node_modules", ".eggs",
             ".raylint_cache"}
# generated trees: protobuf output and anything stamped *_pb2
_GENERATED_MARKERS = ("_pb2.py", "_pb2_grpc.py")

# Bump to force a cache flush even when no analyzer source changed
# (e.g. a semantic change smuggled in via data files).
# 2: SPMD plane — summaries carry mesh-axis/jit-boundary/schedule facts.
RULESET_VERSION = 2

DEFAULT_CACHE_DIR = ".raylint_cache"


class ParsedFile:
    """A scanned file. ``tree`` parses lazily: cache hits never touch
    the parser unless a ``scope="project"`` rule asks for the AST."""

    def __init__(self, path: str, source: str,
                 tree: Optional[ast.Module] = None,
                 suppressions: Optional[Suppressions] = None):
        self.path = path
        self.source = source
        self._tree = tree
        self.suppressions = suppressions if suppressions is not None \
            else Suppressions(source)

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.source, filename=self.path)
        return self._tree


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    files_skipped: int = 0
    files_from_cache: int = 0
    parse_errors: int = 0

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def failing(self, fail_on: str = "warn") -> List[Finding]:
        """Unsuppressed findings at or above the threshold: 'warn'
        fails on everything, 'error' only on errors."""
        if fail_on == "warn":
            return self.unsuppressed
        return [f for f in self.unsuppressed if f.severity == "error"]

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.unsuppressed:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def summary_line(self) -> str:
        # bench.py-style single greppable line for CI diffing
        return (f"RAYLINT files={self.files_scanned} "
                f"findings={len(self.unsuppressed)} "
                f"suppressed={len(self.suppressed)} "
                f"parse_errors={self.parse_errors} "
                f"cached={self.files_from_cache}")

    def to_dict(self) -> dict:
        return {
            "version": SCHEMA_VERSION,
            "summary": {
                "files_scanned": self.files_scanned,
                "files_skipped": self.files_skipped,
                "files_from_cache": self.files_from_cache,
                "parse_errors": self.parse_errors,
                "findings": len(self.unsuppressed),
                "suppressed": len(self.suppressed),
                "by_rule": self.by_rule(),
            },
            "findings": [f.to_dict() for f in self.findings],
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "LintReport":
        """Read back a --json report; accepts schema v1, v2, and v3."""
        if doc.get("version") not in (1, 2, SCHEMA_VERSION):
            raise ValueError(f"unknown raylint schema {doc.get('version')}")
        summary = doc.get("summary", {})
        rep = cls(
            findings=[Finding.from_dict(f) for f in doc.get("findings",
                                                            [])],
            files_scanned=summary.get("files_scanned", 0),
            files_skipped=summary.get("files_skipped", 0),
            files_from_cache=summary.get("files_from_cache", 0),
            parse_errors=summary.get("parse_errors", 0))
        return rep


def _is_generated(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    if any(norm.endswith(m) for m in _GENERATED_MARKERS):
        return True
    # protobuf output dir: skip generated modules, keep the generator
    parts = norm.split("/")
    if "protobuf" in parts[:-1]:
        return parts[-1] not in ("gen.py", "__init__.py")
    return False


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for name in sorted(names):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return [f for f in dict.fromkeys(out) if not _is_generated(f)]


def changed_files(repo_root: str = ".") -> Optional[List[str]]:
    """Paths changed vs HEAD plus untracked files, or None if git is
    unavailable (caller falls back to a full scan)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"], cwd=repo_root,
            capture_output=True, text=True, timeout=30, check=True)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=repo_root, capture_output=True, text=True, timeout=30,
            check=True)
    except (OSError, subprocess.SubprocessError):
        return None
    names = diff.stdout.split() + untracked.stdout.split()
    return [os.path.join(repo_root, n) if repo_root != "." else n
            for n in names if n.endswith(".py")]


# ---------------------------------------------------------------- cache

def ruleset_fingerprint(active: Sequence[Rule]) -> str:
    """Hash of everything that determines a file's analysis result:
    the explicit version knob, the active rule set, and the source of
    the analyzer itself (rules + engine layers). Editing any rule
    invalidates every cache entry — no stale-result footguns."""
    import ray_tpu.devtools.lint.astutil as _astutil
    import ray_tpu.devtools.lint.callgraph as _callgraph
    import ray_tpu.devtools.lint.findings as _findings
    import ray_tpu.devtools.lint.summaries as _summaries
    import ray_tpu.devtools.lint.suppress as _suppress

    h = hashlib.sha256()
    h.update(str(RULESET_VERSION).encode())
    mods = (_astutil, _callgraph, _findings, _summaries, _suppress,
            inspect.getmodule(ruleset_fingerprint))
    for mod in mods:
        try:
            h.update(inspect.getsource(mod).encode())
        except (OSError, TypeError):
            h.update(mod.__name__.encode())
    for rule in sorted(active, key=lambda r: r.id):
        h.update(rule.id.encode())
        try:
            h.update(inspect.getsource(type(rule)).encode())
        except (OSError, TypeError):
            pass
    return h.hexdigest()


def _cache_path(cache_dir: str, path: str) -> str:
    key = hashlib.sha256(os.path.abspath(path).encode()).hexdigest()[:32]
    return os.path.join(cache_dir, f"{key}.json")


def _cache_load(cache_dir: str, path: str, content_sha: str,
                fingerprint: str) -> Optional[dict]:
    try:
        with open(_cache_path(cache_dir, path), encoding="utf-8") as fh:
            entry = json.load(fh)
    except (OSError, ValueError):
        return None
    if entry.get("content_sha") != content_sha \
            or entry.get("fingerprint") != fingerprint:
        return None
    return entry


def _cache_store(cache_dir: str, path: str, entry: dict) -> None:
    try:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = _cache_path(cache_dir, path) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(entry, fh, separators=(",", ":"))
        os.replace(tmp, _cache_path(cache_dir, path))
    except OSError:
        pass  # cache is best-effort; the analysis result is already made


# ------------------------------------------------------------- analysis

def _analyze_file(pf: ParsedFile, file_rules: Sequence[Rule],
                  need_summary: bool):
    """Everything derivable from one file alone: file-scope findings +
    the interprocedural summary. Module-level so tests can spy on it
    (a cache hit must not reach this function)."""
    from ray_tpu.devtools.lint.summaries import summarize

    findings: List[Finding] = []
    for rule in file_rules:
        for f in rule.check(pf):
            f.severity = rule.severity
            findings.append(f)
    summary = summarize(pf.tree, pf.source, pf.path) if need_summary \
        else None
    return findings, summary


def run_lint(paths: Sequence[str],
             rules: Optional[Iterable[Rule]] = None,
             changed_only: bool = False,
             cache_dir: Optional[str] = None,
             graph_depth: Optional[int] = None) -> LintReport:
    from ray_tpu.devtools.lint.callgraph import DEFAULT_DEPTH, ProjectGraph
    from ray_tpu.devtools.lint.summaries import FileSummary

    report = LintReport()
    files = collect_files(paths)
    if changed_only:
        changed = changed_files()
        if changed is not None:
            allowed = {os.path.abspath(c) for c in changed}
            files = [f for f in files if os.path.abspath(f) in allowed]

    active = list(rules) if rules is not None else all_rules()
    file_rules = [r for r in active if r.scope == "file"]
    graph_rules = [r for r in active if r.scope == "graph"]
    project_rules = [r for r in active if r.scope == "project"]
    report_rules = [r for r in active if r.scope == "report"]
    need_summary = bool(graph_rules)
    fingerprint = ruleset_fingerprint(active) if cache_dir else ""

    parsed_files: List[ParsedFile] = []
    summaries: List[FileSummary] = []
    raw: List[Finding] = []

    for path in files:
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError as e:
            report.files_skipped += 1
            report.findings.append(Finding(
                rule="syntax-error", path=path, line=1, col=0,
                message=f"file unreadable: {e}"))
            continue

        entry = None
        content_sha = ""
        if cache_dir:
            content_sha = hashlib.sha256(source.encode()).hexdigest()
            entry = _cache_load(cache_dir, path, content_sha, fingerprint)

        if entry is not None:
            pf = ParsedFile(path, source)
            findings = [Finding.from_dict(d) for d in entry["findings"]]
            for f in findings:
                f.path = path
                f.suppressed = False
            if need_summary:
                if entry.get("summary") is None:
                    entry = None    # cached without summaries: recompute
                else:
                    summary = FileSummary.from_json(entry["summary"])
                    summary.path = path
            if entry is not None:
                report.files_from_cache += 1
                parsed_files.append(pf)
                raw.extend(findings)
                if need_summary:
                    summaries.append(summary)
                continue

        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            report.parse_errors += 1
            report.findings.append(Finding(
                rule="syntax-error", path=path,
                line=e.lineno or 1, col=e.offset or 0,
                message=f"file does not parse: {e.msg}",
                hint="raylint skipped this file's rules; fix the syntax"))
            continue
        pf = ParsedFile(path, source, tree=tree)
        findings, summary = _analyze_file(pf, file_rules, need_summary)
        parsed_files.append(pf)
        raw.extend(findings)
        if need_summary and summary is not None:
            summaries.append(summary)
        if cache_dir:
            _cache_store(cache_dir, path, {
                "content_sha": content_sha, "fingerprint": fingerprint,
                "findings": [f.to_dict() for f in findings],
                "summary": summary.to_json() if summary is not None
                else None})

    report.files_scanned = len(parsed_files)

    if graph_rules:
        graph = ProjectGraph(
            summaries,
            depth=graph_depth if graph_depth is not None else DEFAULT_DEPTH)
        for rule in graph_rules:
            for f in rule.check_graph(graph):
                f.severity = rule.severity
                raw.append(f)
    for rule in project_rules:
        for f in rule.check_project(parsed_files):
            f.severity = rule.severity
            raw.append(f)

    active_ids = {r.id for r in active}
    for rule in report_rules:
        for f in rule.check_report(parsed_files, list(raw), active_ids):
            f.severity = rule.severity
            raw.append(f)

    file_wide_only = {r.id for r in active if r.file_wide_only}
    supp_by_path = {pf.path: pf.suppressions for pf in parsed_files}
    for f in raw:
        supp = supp_by_path.get(f.path)
        if supp is not None and supp.is_suppressed(
                f.rule, f.line, file_only=f.rule in file_wide_only):
            f.suppressed = True
    report.findings.extend(raw)
    report.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return report
