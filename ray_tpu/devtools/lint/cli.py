"""raylint command line: ``python -m ray_tpu.devtools.lint [paths]``.

Exit code 0 when every finding is suppressed (or there are none),
1 when unsuppressed findings remain, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ray_tpu.devtools.lint.engine import run_lint
from ray_tpu.devtools.lint.registry import all_rules


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="raylint: distributed-correctness static analysis "
                    "for ray_tpu")
    parser.add_argument("paths", nargs="*", default=["ray_tpu"],
                        help="files or directories to lint "
                             "(default: ray_tpu)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the machine-readable report (stable "
                             "schema, version 1) instead of text")
    parser.add_argument("--changed-only", action="store_true",
                        help="limit to files changed vs git HEAD plus "
                             "untracked files (fast pre-commit mode); "
                             "falls back to a full scan without git")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="RULE-ID",
                        help="run only this rule (repeatable)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings in text mode")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:24s} {r.doc}")
        return 0
    if args.rule:
        known = {r.id for r in rules}
        bad = [r for r in args.rule if r not in known]
        if bad:
            print(f"unknown rule(s): {', '.join(bad)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in set(args.rule)]

    report = run_lint(args.paths, rules=rules,
                      changed_only=args.changed_only)

    if args.as_json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
        # bench.py-style greppable one-liner; stderr keeps stdout pure JSON
        print(report.summary_line(), file=sys.stderr)
    else:
        for f in report.findings:
            if f.suppressed and not args.show_suppressed:
                continue
            print(f.render())
        print(report.summary_line())
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
