"""raylint command line: ``python -m ray_tpu.devtools.lint [paths]``.

Exit code 0 when no finding clears the ``--fail-on`` threshold (all
suppressed, or warn-only findings under ``--fail-on error``), 1 when
failing findings remain, 2 on usage errors.

Results are cached under ``.raylint_cache/`` keyed by (file content
sha, ruleset fingerprint); a warm run over an unchanged tree skips
parsing and per-file analysis entirely. ``--no-cache`` disables it,
``--cache-dir`` relocates it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ray_tpu.devtools.lint.engine import DEFAULT_CACHE_DIR, run_lint
from ray_tpu.devtools.lint.registry import all_rules


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="raylint: distributed-correctness static analysis "
                    "for ray_tpu")
    parser.add_argument("paths", nargs="*", default=["ray_tpu"],
                        help="files or directories to lint "
                             "(default: ray_tpu)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the machine-readable report (stable "
                             "schema, version 3) instead of text")
    parser.add_argument("--changed-only", action="store_true",
                        help="limit to files changed vs git HEAD plus "
                             "untracked files (fast pre-commit mode); "
                             "falls back to a full scan without git")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="RULE-ID",
                        help="run only this rule (repeatable)")
    parser.add_argument("--fail-on", choices=("error", "warn"),
                        default="warn",
                        help="minimum severity that fails the run "
                             "(default: warn — any unsuppressed finding)")
    parser.add_argument("--cache-dir", default=DEFAULT_CACHE_DIR,
                        metavar="DIR",
                        help="result cache location "
                             f"(default: {DEFAULT_CACHE_DIR})")
    parser.add_argument("--no-cache", action="store_true",
                        help="analyze every file from scratch")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings in text mode")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:24s} [{r.severity}] {r.doc}")
        return 0
    if args.rule:
        known = {r.id for r in rules}
        bad = [r for r in args.rule if r not in known]
        if bad:
            print(f"unknown rule(s): {', '.join(bad)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in set(args.rule)]

    report = run_lint(args.paths, rules=rules,
                      changed_only=args.changed_only,
                      cache_dir=None if args.no_cache else args.cache_dir)

    if args.as_json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
        # bench.py-style greppable one-liner; stderr keeps stdout pure JSON
        print(report.summary_line(), file=sys.stderr)
    else:
        for f in report.findings:
            if f.suppressed and not args.show_suppressed:
                continue
            print(f.render())
        print(report.summary_line())
    return 1 if report.failing(args.fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
