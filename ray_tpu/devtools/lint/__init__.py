"""raylint — distributed-correctness static analysis for ray_tpu.

Run it:            python -m ray_tpu.devtools.lint [paths] [--json]
Library entry:     run_lint(paths) -> LintReport
Rule catalog:      python -m ray_tpu.devtools.lint --list-rules
Suppress a site:   trailing `# raylint: disable=<rule-id> -- why`

The tier-1 gate (tests/test_lint.py) runs the analyzer over ray_tpu/
and fails on any unsuppressed finding, so the rule suite is a ratchet:
a pattern added here can never regress back into the tree.
"""

from ray_tpu.devtools.lint.engine import (LintReport, ParsedFile,  # noqa: F401
                                          collect_files, run_lint)
from ray_tpu.devtools.lint.findings import Finding  # noqa: F401
from ray_tpu.devtools.lint.registry import (Rule, all_rules,  # noqa: F401
                                            register, rule_ids)
