"""Rule base class + registry.

A rule is a class with a unique ``id``, a one-line ``doc`` (shown by
``--list-rules``), and a scope:

- ``scope = "file"``: ``check(parsed)`` is called once per parsed file
  and yields Findings for that file only.
- ``scope = "project"``: ``check_project(parsed_files)`` is called once
  with every parsed file, for rules that need cross-file state (e.g.
  config-knob-drift's defined-but-never-read direction).

Register with the ``@register`` decorator; ``rules/__init__.py`` imports
every rule module so importing the package populates the registry.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Type

from ray_tpu.devtools.lint.findings import Finding

_REGISTRY: Dict[str, Type["Rule"]] = {}


class Rule:
    id: str = ""
    doc: str = ""
    hint: str = ""
    scope: str = "file"  # "file" | "project"

    def check(self, parsed) -> Iterable[Finding]:  # file-scope rules
        return ()

    def check_project(self, parsed_files) -> Iterable[Finding]:  # project
        return ()


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> List[Rule]:
    # import for side effect: rule modules self-register
    from ray_tpu.devtools.lint import rules  # noqa: F401

    return [cls() for _, cls in sorted(_REGISTRY.items())]


def rule_ids() -> List[str]:
    from ray_tpu.devtools.lint import rules  # noqa: F401

    return sorted(_REGISTRY)
