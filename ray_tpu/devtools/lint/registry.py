"""Rule base class + registry.

A rule is a class with a unique ``id``, a one-line ``doc`` (shown by
``--list-rules``), a ``severity`` (``"error"`` or ``"warn"``, stamped
onto every Finding the rule emits), and a scope:

- ``scope = "file"``: ``check(parsed)`` is called once per parsed file
  and yields Findings for that file only.
- ``scope = "graph"``: ``check_graph(graph)`` is called once with the
  :class:`~ray_tpu.devtools.lint.callgraph.ProjectGraph` built from
  every file's summary — the home of interprocedural rules (call-graph
  reachability, lock-order, actor cycles). Graph rules never see ASTs,
  which is what lets the engine serve them from the result cache.
- ``scope = "project"``: ``check_project(parsed_files)`` is called once
  with every parsed file, for cross-file rules that genuinely need raw
  ASTs (none in-tree today; parsing is lazy, so using this scope
  forfeits the cache's parse-skipping).
- ``scope = "report"``: ``check_report(parsed_files, findings,
  active_ids)`` runs after every other rule with the raw (pre-
  suppression) findings — meta-rules like useless-suppression.

``file_wide_only = True`` makes the rule honor only ``disable-file=``
suppressions (line-level disables are ignored).

Register with the ``@register`` decorator; ``rules/__init__.py`` imports
every rule module so importing the package populates the registry.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Type

from ray_tpu.devtools.lint.findings import Finding

_REGISTRY: Dict[str, Type["Rule"]] = {}


class Rule:
    id: str = ""
    doc: str = ""
    hint: str = ""
    scope: str = "file"  # "file" | "graph" | "project" | "report"
    severity: str = "error"  # "error" | "warn"
    file_wide_only: bool = False

    def check(self, parsed) -> Iterable[Finding]:  # file-scope rules
        return ()

    def check_graph(self, graph) -> Iterable[Finding]:  # graph scope
        return ()

    def check_project(self, parsed_files) -> Iterable[Finding]:  # project
        return ()

    def check_report(self, parsed_files, findings,
                     active_ids) -> Iterable[Finding]:  # report scope
        return ()


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> List[Rule]:
    # import for side effect: rule modules self-register
    from ray_tpu.devtools.lint import rules  # noqa: F401

    return [cls() for _, cls in sorted(_REGISTRY.items())]


def rule_ids() -> List[str]:
    from ray_tpu.devtools.lint import rules  # noqa: F401

    return sorted(_REGISTRY)
