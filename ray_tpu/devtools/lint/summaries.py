"""Per-file analysis summaries for interprocedural raylint rules.

One :class:`FileSummary` per parsed file captures everything the
whole-program phase needs — per-function call sites, blocking
operations, lock acquisitions, collective invocations, compiled-channel
ops, rank-conditional branches, and per-class attribute types — as
plain JSON-able data. The project call graph (callgraph.py) is built
purely from summaries, never from ASTs, which is what makes the
result cache work: a cache hit loads the summary and skips both the
parse and the per-file extraction, and graph rules still see the file.

Extraction is deliberately conservative: a receiver or callee the
flow-insensitive pass cannot resolve is recorded raw and dropped at
resolution time, trading recall for a near-zero false-positive rate
(the tier-1 gate keeps the tree clean, so every false positive is a
build break).
"""

from __future__ import annotations

import ast
import os
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.devtools.lint.astutil import (FuncNode, decorator_names,
                                           dotted_name, walk_scope)

# Blocking object-store reads (same exact-chain table blocking_async
# uses, plus the bare names `from ray_tpu import get/wait` would bind).
BLOCKING_GET = {
    "ray_tpu.get", "runtime.get", "rt.get", "_runtime.get", "_rt.get",
}
BLOCKING_WAIT = {
    "ray_tpu.wait", "runtime.wait", "rt.wait", "_runtime.wait", "_rt.wait",
}

COLLECTIVE_OPS = {
    "allreduce", "allgather", "broadcast", "reducescatter", "barrier",
    "allreduce_async", "allgather_async", "broadcast_async",
    "reducescatter_async", "barrier_async",
}
_COLLECTIVE_RECEIVERS = ("collective", "col", "group", "comm")
_RANK_WORDS = ("rank", "is_leader", "is_root", "is_coordinator")

_LOCK_CTORS = {
    "threading.Lock": "lock", "Lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "cond", "Condition": "cond",
}
_THREAD_CTORS = {"threading.Thread", "Thread", "multiprocessing.Process",
                 "Process"}
CHANNEL_OPS = {"execute", "teardown", "close", "put", "enqueue", "write",
               "experimental_compile",
               # KV-handoff lifecycle (serve/kv_transfer.py): exporters
               # and standing decode channels share the protocol —
               # export/adopt are channel traffic, close/teardown ends it
               "adopt", "export"}
SHUTDOWN_METHODS = {"shutdown", "stop", "close", "teardown", "drain",
                    "_stop", "_shutdown", "_close", "_teardown",
                    "__exit__", "__del__", "atexit_handler"}


def collective_op(call: ast.Call) -> str:
    """The collective op name if this call is one, else ''."""
    name = dotted_name(call.func)
    parts = name.split(".")
    if parts[-1] not in COLLECTIVE_OPS:
        return ""
    if len(parts) > 1 and not any(w in p for p in parts[:-1]
                                  for w in _COLLECTIVE_RECEIVERS):
        return ""
    return parts[-1]


def mentions_rank(test: ast.AST) -> bool:
    for node in ast.walk(test):
        word = None
        if isinstance(node, ast.Name):
            word = node.id
        elif isinstance(node, ast.Attribute):
            word = node.attr
        if word and any(w in word.lower() for w in _RANK_WORDS):
            return True
    return False


def module_name_for(path: str) -> str:
    """Best-effort dotted module for a file path: the part from the last
    `ray_tpu` component down, else the bare stem (fixtures, tmp files)."""
    norm = os.path.normpath(path).replace(os.sep, "/")
    parts = [p for p in norm.split("/") if p]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts.pop()
    for anchor in ("ray_tpu",):
        if anchor in parts:
            return ".".join(parts[parts.index(anchor):])
    return parts[-1] if parts else "?"


def _ctor_tag(value: ast.AST) -> str:
    """'lock'|'rlock'|'cond'|'thread'|'compiled'|'actor:<Cls>'|'' for the
    right-hand side of an assignment."""
    if not isinstance(value, ast.Call):
        return ""
    name = dotted_name(value.func)
    if name in _LOCK_CTORS:
        return _LOCK_CTORS[name]
    if name in _THREAD_CTORS:
        return "thread"
    tail = name.split(".")[-1]
    if tail == "experimental_compile":
        return "compiled"
    if tail == "remote":
        # Cls.remote(...) or Cls.options(...).remote(...)
        parts = name.split(".")
        if len(parts) == 2 and parts[0][:1].isupper():
            return f"actor:{parts[0]}"
        if isinstance(value.func, ast.Attribute) \
                and isinstance(value.func.value, ast.Call):
            inner = dotted_name(value.func.value.func)
            ip = inner.split(".")
            if ip[-1] == "options" and len(ip) == 2 \
                    and ip[0][:1].isupper():
                return f"actor:{ip[0]}"
    return ""


def _remote_targets(node: ast.AST) -> List[Dict[str, str]]:
    """`recv.meth.remote(...)` call sites anywhere under ``node``:
    [{'recv': 'self._replica', 'method': 'queue_len'}, ...]."""
    out = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = dotted_name(sub.func)
        parts = name.split(".")
        if parts[-1] != "remote" or len(parts) < 3:
            continue
        out.append({"recv": ".".join(parts[:-2]), "method": parts[-2]})
    return out


@dataclass
class FunctionSummary:
    qualname: str                     # "Class.method" | "fn" | "fn.inner"
    line: int
    cls: str = ""                     # enclosing class name, "" if none
    is_actor: bool = False            # enclosing class is @remote-decorated
    is_async: bool = False
    calls: List[List[Any]] = field(default_factory=list)   # [name, ln, col]
    blocking: List[Dict[str, Any]] = field(default_factory=list)
    collectives: List[List[Any]] = field(default_factory=list)
    rank_branches: List[Dict[str, Any]] = field(default_factory=list)
    lock_sections: List[Dict[str, Any]] = field(default_factory=list)
    channel_ops: List[Dict[str, Any]] = field(default_factory=list)
    local_types: Dict[str, str] = field(default_factory=dict)


@dataclass
class ClassSummary:
    name: str
    line: int
    is_actor: bool = False
    bases: List[str] = field(default_factory=list)
    methods: List[str] = field(default_factory=list)
    attr_types: Dict[str, str] = field(default_factory=dict)
    attr_lines: Dict[str, int] = field(default_factory=dict)


@dataclass
class FileSummary:
    path: str
    module: str
    functions: List[FunctionSummary] = field(default_factory=list)
    classes: List[ClassSummary] = field(default_factory=list)
    imports: Dict[str, str] = field(default_factory=dict)
    module_types: Dict[str, str] = field(default_factory=dict)
    config: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "FileSummary":
        fs = cls(path=doc["path"], module=doc["module"],
                 imports=doc.get("imports", {}),
                 module_types=doc.get("module_types", {}),
                 config=doc.get("config", {}))
        fs.functions = [FunctionSummary(**f) for f in doc.get("functions",
                                                              [])]
        fs.classes = [ClassSummary(**c) for c in doc.get("classes", [])]
        return fs


def _is_actor_class(node: ast.ClassDef) -> bool:
    return any(d == "remote" or d.endswith(".remote")
               for d in decorator_names(node))


def _imports_of(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for alias in node.names:
                out[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return out


def _span(node: ast.AST) -> Tuple[int, int]:
    return node.lineno, getattr(node, "end_lineno", node.lineno)


class _FunctionExtractor:
    """Builds one FunctionSummary from an ast function node."""

    def __init__(self, fn: ast.AST, qualname: str, cls: str,
                 is_actor: bool, bare_gets: Dict[str, str]):
        self.fn = fn
        self.bare_gets = bare_gets
        self.s = FunctionSummary(
            qualname=qualname, line=fn.lineno, cls=cls, is_actor=is_actor,
            is_async=isinstance(fn, ast.AsyncFunctionDef))

    def run(self) -> FunctionSummary:
        s = self.s
        rank_arm_nodes = []   # nodes already claimed by a rank branch
        for node in walk_scope(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tag = _ctor_tag(node.value)
                if tag:
                    s.local_types[node.targets[0].id] = tag
            if isinstance(node, ast.If) and mentions_rank(node.test):
                s.rank_branches.append({
                    "line": node.lineno,
                    "arms": [self._arm(node.body), self._arm(node.orelse)],
                })
                rank_arm_nodes.append(node)
            elif isinstance(node, ast.IfExp) and mentions_rank(node.test):
                s.rank_branches.append({
                    "line": node.lineno,
                    "arms": [self._arm([node.body]),
                             self._arm([node.orelse])],
                })
            elif isinstance(node, ast.With):
                self._with(node)
            elif isinstance(node, ast.Call):
                self._call(node)
        self._channel_ops()
        return s

    # -- pieces ----------------------------------------------------------
    def _arm(self, nodes) -> Dict[str, Any]:
        ops, calls = [], []
        for n in nodes:
            for sub in ast.walk(n):
                if not isinstance(sub, ast.Call):
                    continue
                op = collective_op(sub)
                if op:
                    ops.append([op, sub.lineno, sub.col_offset])
                else:
                    calls.append([dotted_name(sub.func), sub.lineno,
                                  sub.col_offset])
        return {"ops": ops, "calls": calls}

    def _call(self, node: ast.Call) -> None:
        s = self.s
        name = dotted_name(node.func)
        parts = name.split(".")
        s.calls.append([name, node.lineno, node.col_offset])
        op = collective_op(node)
        if op:
            s.collectives.append([op, node.lineno, node.col_offset])
        short = name[5:] if name.startswith("self.") else name
        if name in BLOCKING_GET or short in BLOCKING_GET \
                or (len(parts) == 1
                    and self.bare_gets.get(parts[0]) == "get"):
            s.blocking.append({
                "kind": "get", "name": name, "line": node.lineno,
                "col": node.col_offset,
                "targets": [t for a in node.args + [k.value for k in
                                                    node.keywords]
                            for t in _remote_targets(a)]})
        elif name in BLOCKING_WAIT or short in BLOCKING_WAIT \
                or (len(parts) == 1
                    and self.bare_gets.get(parts[0]) == "wait"):
            s.blocking.append({"kind": "wait", "name": name,
                               "line": node.lineno, "col": node.col_offset,
                               "targets": []})
        elif name == "time.sleep":
            secs: Optional[float] = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, (int, float)):
                secs = float(node.args[0].value)
            s.blocking.append({"kind": "sleep", "name": name,
                               "line": node.lineno, "col": node.col_offset,
                               "seconds": secs, "targets": []})
        elif parts[-1] == "join" and len(parts) >= 2 and parts[0] != "?":
            s.blocking.append({"kind": "join", "name": name,
                               "recv": ".".join(parts[:-1]),
                               "line": node.lineno, "col": node.col_offset,
                               "targets": []})
        elif parts[-1] == "wait" and len(parts) >= 2 and parts[0] != "?":
            # cond.wait() — blocking unless it is the section's own lock
            s.blocking.append({"kind": "cond-wait", "name": name,
                               "recv": ".".join(parts[:-1]),
                               "line": node.lineno, "col": node.col_offset,
                               "targets": []})
        elif parts[-1] == "acquire" and len(parts) >= 2 \
                and parts[0] != "?":
            self.s.lock_sections.append({
                "expr": ".".join(parts[:-1]), "line": node.lineno,
                "col": node.col_offset, "span": [node.lineno, node.lineno],
                "acquire_only": True})

    def _with(self, node: ast.With) -> None:
        body_start = node.body[0].lineno if node.body else node.lineno
        group = id(node) & 0xFFFFFFFF
        for gi, item in enumerate(node.items):
            expr = item.context_expr
            if isinstance(expr, (ast.Name, ast.Attribute)):
                name = dotted_name(expr)
                if name.startswith("?"):
                    continue
                self.s.lock_sections.append({
                    "expr": name, "line": node.lineno,
                    "col": node.col_offset,
                    "span": [body_start, _span(node)[1]],
                    "acquire_only": False, "group": group,
                    "group_idx": gi})

    def _channel_ops(self) -> None:
        """Ordered channel ops with (block, idx) identity so protocol
        rules can reason about straight-line statement order."""
        block_counter = [0]
        BLOCK_ATTRS = ("body", "orelse", "finalbody")

        def header_calls(stmt):
            """Calls in a statement outside its nested blocks/scopes."""
            skip = set()
            for attr in BLOCK_ATTRS:
                for s in getattr(stmt, attr, None) or ():
                    skip.add(id(s))
            for h in getattr(stmt, "handlers", None) or ():
                skip.add(id(h))
            stack = [c for c in ast.iter_child_nodes(stmt)
                     if id(c) not in skip]
            while stack:
                n = stack.pop()
                if isinstance(n, FuncNode + (ast.Lambda,)):
                    continue
                if isinstance(n, ast.Call):
                    yield n
                stack.extend(ast.iter_child_nodes(n))

        def visit_block(stmts) -> None:
            block_counter[0] += 1
            bid = block_counter[0]
            for idx, stmt in enumerate(stmts):
                for sub in header_calls(stmt):
                    name = dotted_name(sub.func)
                    parts = name.split(".")
                    if len(parts) >= 2 and parts[-1] in CHANNEL_OPS \
                            and parts[0] != "?":
                        self.s.channel_ops.append({
                            "recv": ".".join(parts[:-1]),
                            "op": parts[-1], "line": sub.lineno,
                            "col": sub.col_offset, "block": bid,
                            "idx": idx})
                for attr in BLOCK_ATTRS:
                    sub_stmts = getattr(stmt, attr, None)
                    if sub_stmts:
                        visit_block(sub_stmts)
                for h in getattr(stmt, "handlers", None) or ():
                    visit_block(h.body)

        visit_block(self.fn.body)


def _class_summary(node: ast.ClassDef, module: str) -> ClassSummary:
    cs = ClassSummary(name=node.name, line=node.lineno,
                      is_actor=_is_actor_class(node),
                      bases=[dotted_name(b).split(".")[-1]
                             for b in node.bases])
    for st in node.body:
        if isinstance(st, FuncNode):
            cs.methods.append(st.name)
        elif isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    tag = _ctor_tag(st.value)
                    if tag:
                        cs.attr_types[t.id] = tag
                        cs.attr_lines[t.id] = st.lineno
    # self.X = <ctor> anywhere in the class's methods
    for fn in node.body:
        if not isinstance(fn, FuncNode):
            continue
        for sub in walk_scope(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    tag = _ctor_tag(sub.value)
                    if tag and t.attr not in cs.attr_types:
                        cs.attr_types[t.attr] = tag
                        cs.attr_lines[t.attr] = sub.lineno
    return cs


def summarize(tree: ast.Module, source: str, path: str) -> FileSummary:
    """The per-file half of the interprocedural analysis; pure function
    of the file content, which is what makes it cacheable."""
    module = module_name_for(path)
    fs = FileSummary(path=path, module=module)
    fs.imports = _imports_of(tree)
    bare_gets = {local: target.rsplit(".", 1)[1]
                 for local, target in fs.imports.items()
                 if target in ("ray_tpu.get", "ray_tpu.wait")}

    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tag = _ctor_tag(node.value)
                    if tag:
                        fs.module_types[t.id] = tag

    # parent map for qualnames
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node

    def qualname_of(fn: ast.AST) -> Tuple[str, str, bool]:
        names: List[str] = [fn.name]
        cls, is_actor = "", False
        cur = parents.get(id(fn))
        while cur is not None and not isinstance(cur, ast.Module):
            if isinstance(cur, ast.ClassDef):
                if not cls:
                    cls, is_actor = cur.name, _is_actor_class(cur)
                names.append(cur.name)
            elif isinstance(cur, FuncNode):
                names.append(cur.name)
            cur = parents.get(id(cur))
        return ".".join(reversed(names)), cls, is_actor

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            fs.classes.append(_class_summary(node, module))
        elif isinstance(node, FuncNode):
            qn, cls, is_actor = qualname_of(node)
            fs.functions.append(_FunctionExtractor(
                node, qn, cls, is_actor, bare_gets).run())

    from ray_tpu.devtools.lint.rules.config_drift import extract_config
    fs.config = extract_config(tree, source, path)
    return fs
