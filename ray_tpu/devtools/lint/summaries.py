"""Per-file analysis summaries for interprocedural raylint rules.

One :class:`FileSummary` per parsed file captures everything the
whole-program phase needs — per-function call sites, blocking
operations, lock acquisitions, collective invocations, compiled-channel
ops, rank-conditional branches, and per-class attribute types — as
plain JSON-able data. The project call graph (callgraph.py) is built
purely from summaries, never from ASTs, which is what makes the
result cache work: a cache hit loads the summary and skips both the
parse and the per-file extraction, and graph rules still see the file.

Extraction is deliberately conservative: a receiver or callee the
flow-insensitive pass cannot resolve is recorded raw and dropped at
resolution time, trading recall for a near-zero false-positive rate
(the tier-1 gate keeps the tree clean, so every false positive is a
build break).
"""

from __future__ import annotations

import ast
import os
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.devtools.lint.astutil import (FuncNode, decorator_names,
                                           dotted_name, walk_scope)

# Blocking object-store reads (same exact-chain table blocking_async
# uses, plus the bare names `from ray_tpu import get/wait` would bind).
BLOCKING_GET = {
    "ray_tpu.get", "runtime.get", "rt.get", "_runtime.get", "_rt.get",
}
BLOCKING_WAIT = {
    "ray_tpu.wait", "runtime.wait", "rt.wait", "_runtime.wait", "_rt.wait",
}

COLLECTIVE_OPS = {
    "allreduce", "allgather", "broadcast", "reducescatter", "barrier",
    "allreduce_async", "allgather_async", "broadcast_async",
    "reducescatter_async", "barrier_async",
}
_COLLECTIVE_RECEIVERS = ("collective", "col", "group", "comm")
_RANK_WORDS = ("rank", "is_leader", "is_root", "is_coordinator")

_LOCK_CTORS = {
    "threading.Lock": "lock", "Lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "cond", "Condition": "cond",
}
_THREAD_CTORS = {"threading.Thread", "Thread", "multiprocessing.Process",
                 "Process"}
# --- SPMD plane tables ------------------------------------------------------
# Device collectives emitted inside jitted/shard_map'd bodies. These are
# rendezvous points exactly like the host ops above: every rank must
# issue them in the same order.
LAX_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                   "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                   "pswapaxes"}
# Axis queries: not rendezvous ops, but their axis argument must name a
# declared mesh axis all the same.
LAX_AXIS_QUERIES = {"axis_index", "axis_size"}
# Wall-clock reads: in a jitted body these execute once at trace time
# and bake a constant into the compiled program.
WALL_CLOCK = {"time.time", "time.perf_counter", "time.monotonic",
              "time.time_ns", "datetime.now", "datetime.datetime.now",
              "datetime.utcnow"}
_METRIC_RECV_WORDS = ("metric", "counter", "gauge", "hist")
# Host-collective calls that carry a group name, and which argument
# position it rides in (kwarg `group_name=` always wins).
HOST_GROUP_ARG = {
    "allreduce": 1, "allgather": 1, "reducescatter": 1, "broadcast": 2,
    "barrier": 0,
    "allreduce_async": 1, "allgather_async": 1, "reducescatter_async": 1,
    "broadcast_async": 2, "barrier_async": 0,
    "init_collective_group": 2, "destroy_collective_group": 0,
    "init_host_collective": 0, "destroy_host_collective": 0,
}

CHANNEL_OPS = {"execute", "teardown", "close", "put", "enqueue", "write",
               "experimental_compile",
               # KV-handoff lifecycle (serve/kv_transfer.py): exporters
               # and standing decode channels share the protocol —
               # export/adopt are channel traffic, close/teardown ends it
               "adopt", "export"}
SHUTDOWN_METHODS = {"shutdown", "stop", "close", "teardown", "drain",
                    "_stop", "_shutdown", "_close", "_teardown",
                    "__exit__", "__del__", "atexit_handler"}


def collective_op(call: ast.Call) -> str:
    """The collective op name if this call is one, else ''."""
    name = dotted_name(call.func)
    parts = name.split(".")
    if parts[-1] not in COLLECTIVE_OPS:
        return ""
    if len(parts) > 1 and not any(w in p for p in parts[:-1]
                                  for w in _COLLECTIVE_RECEIVERS):
        return ""
    return parts[-1]


def _axis_strs(node: Optional[ast.AST]) -> List[str]:
    """String literals in a Constant or Tuple/List/Set literal. Dynamic
    expressions yield [] — the SPMD pass only reasons about literals."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _int_elems(node: Optional[ast.AST]) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)
                and not isinstance(e.value, bool)]
    return []


def _spec_arity(node: Optional[ast.AST]) -> int:
    """Arity of an in_specs/out_specs literal: len() for a tuple/list
    literal, -1 for anything else (single spec, variable, pytree)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    return -1


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def jit_decorator(fn: ast.AST) -> Dict[str, Any]:
    """Jit-boundary facts from a function's decorator stack, seeing
    through ``functools.partial(jax.jit, ...)`` wrapping. {} if the
    function is not jit/sharded_jit/shard_map decorated."""
    for dec in getattr(fn, "decorator_list", []):
        call = dec if isinstance(dec, ast.Call) else None
        name = dotted_name(call.func if call else dec)
        tail = name.split(".")[-1]
        if tail == "partial" and call and call.args:
            name = dotted_name(call.args[0])
            tail = name.split(".")[-1]
        if tail not in ("jit", "sharded_jit", "shard_map"):
            continue
        if tail == "jit" and not (name in ("jit", "jax.jit")
                                  or name.endswith(".jit")):
            continue
        out = {"kind": tail, "line": dec.lineno, "in_arity": -1,
               "out_arity": -1, "static_argnums": [], "donate_argnums": []}
        for kw in (call.keywords if call else []):
            if kw.arg == "in_specs":
                out["in_arity"] = _spec_arity(kw.value)
            elif kw.arg == "out_specs":
                out["out_arity"] = _spec_arity(kw.value)
            elif kw.arg == "static_argnums":
                out["static_argnums"] = _int_elems(kw.value)
            elif kw.arg == "donate_argnums":
                out["donate_argnums"] = _int_elems(kw.value)
        return out
    return {}


def _returns_arity(fn: ast.AST) -> int:
    """Statically-known return arity: N when every return in the body
    is a bare N-tuple literal, else -1 (unknown)."""
    arity: Optional[int] = None
    for node in walk_scope(fn):
        if not isinstance(node, ast.Return):
            continue
        if not isinstance(node.value, ast.Tuple):
            return -1
        k = len(node.value.elts)
        if arity is None:
            arity = k
        elif arity != k:
            return -1
    return -1 if arity is None else arity


def mentions_rank(test: ast.AST) -> bool:
    for node in ast.walk(test):
        word = None
        if isinstance(node, ast.Name):
            word = node.id
        elif isinstance(node, ast.Attribute):
            word = node.attr
        if word and any(w in word.lower() for w in _RANK_WORDS):
            return True
    return False


def module_name_for(path: str) -> str:
    """Best-effort dotted module for a file path: the part from the last
    `ray_tpu` component down, else the bare stem (fixtures, tmp files)."""
    norm = os.path.normpath(path).replace(os.sep, "/")
    parts = [p for p in norm.split("/") if p]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts.pop()
    for anchor in ("ray_tpu",):
        if anchor in parts:
            return ".".join(parts[parts.index(anchor):])
    return parts[-1] if parts else "?"


def _ctor_tag(value: ast.AST) -> str:
    """'lock'|'rlock'|'cond'|'thread'|'compiled'|'actor:<Cls>'|'' for the
    right-hand side of an assignment."""
    if not isinstance(value, ast.Call):
        return ""
    name = dotted_name(value.func)
    if name in _LOCK_CTORS:
        return _LOCK_CTORS[name]
    if name in _THREAD_CTORS:
        return "thread"
    tail = name.split(".")[-1]
    if tail == "experimental_compile":
        return "compiled"
    if tail == "remote":
        # Cls.remote(...) or Cls.options(...).remote(...)
        parts = name.split(".")
        if len(parts) == 2 and parts[0][:1].isupper():
            return f"actor:{parts[0]}"
        if isinstance(value.func, ast.Attribute) \
                and isinstance(value.func.value, ast.Call):
            inner = dotted_name(value.func.value.func)
            ip = inner.split(".")
            if ip[-1] == "options" and len(ip) == 2 \
                    and ip[0][:1].isupper():
                return f"actor:{ip[0]}"
    return ""


def _remote_targets(node: ast.AST) -> List[Dict[str, str]]:
    """`recv.meth.remote(...)` call sites anywhere under ``node``:
    [{'recv': 'self._replica', 'method': 'queue_len'}, ...]."""
    out = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = dotted_name(sub.func)
        parts = name.split(".")
        if parts[-1] != "remote" or len(parts) < 3:
            continue
        out.append({"recv": ".".join(parts[:-2]), "method": parts[-2]})
    return out


@dataclass
class FunctionSummary:
    qualname: str                     # "Class.method" | "fn" | "fn.inner"
    line: int
    cls: str = ""                     # enclosing class name, "" if none
    is_actor: bool = False            # enclosing class is @remote-decorated
    is_async: bool = False
    calls: List[List[Any]] = field(default_factory=list)   # [name, ln, col]
    blocking: List[Dict[str, Any]] = field(default_factory=list)
    collectives: List[List[Any]] = field(default_factory=list)
    rank_branches: List[Dict[str, Any]] = field(default_factory=list)
    lock_sections: List[Dict[str, Any]] = field(default_factory=list)
    channel_ops: List[Dict[str, Any]] = field(default_factory=list)
    local_types: Dict[str, str] = field(default_factory=dict)
    # SPMD plane extract (all keys optional, omitted when empty):
    #   jit            {kind,line,in_arity,out_arity,static_argnums,
    #                   donate_argnums} — this fn is jit-decorated
    #   jit_wraps      [[kind, target, line, in_arity, out_arity]] —
    #                  jax.jit(f)/shard_map(f, ...) call sites in the body
    #   axis_uses      [[axis, line, col, ctx]] — literal axis names
    #   axis_decls     [[axis, line]] — mesh constructions declaring axes
    #   schedule       ordered ["op",op,axis_or_group,ln,col] |
    #                  ["call",name,ln,col] events outside rank branches
    #   rank_scheds    [{line, arms: [events, events]}]
    #   group_literals [[op, name, line, col]] — hardcoded group strings
    #   host_effects   [[kind, name, line, col]] — wall-clock/metric calls
    #   params         [n_pos, n_required, has_varargs, first_param]
    #   returns        statically-known tuple return arity, -1 unknown
    spmd: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ClassSummary:
    name: str
    line: int
    is_actor: bool = False
    bases: List[str] = field(default_factory=list)
    methods: List[str] = field(default_factory=list)
    attr_types: Dict[str, str] = field(default_factory=dict)
    attr_lines: Dict[str, int] = field(default_factory=dict)


@dataclass
class FileSummary:
    path: str
    module: str
    functions: List[FunctionSummary] = field(default_factory=list)
    classes: List[ClassSummary] = field(default_factory=list)
    imports: Dict[str, str] = field(default_factory=dict)
    module_types: Dict[str, str] = field(default_factory=dict)
    config: Dict[str, Any] = field(default_factory=dict)
    # module-level SPMD facts: axis_decls [[axis, line]] from constants
    # like AXIS_ORDER = ("dp", "pp", ...)
    spmd: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "FileSummary":
        fs = cls(path=doc["path"], module=doc["module"],
                 imports=doc.get("imports", {}),
                 module_types=doc.get("module_types", {}),
                 config=doc.get("config", {}),
                 spmd=doc.get("spmd", {}))
        fs.functions = [FunctionSummary(**f) for f in doc.get("functions",
                                                              [])]
        fs.classes = [ClassSummary(**c) for c in doc.get("classes", [])]
        return fs


def _is_actor_class(node: ast.ClassDef) -> bool:
    return any(d == "remote" or d.endswith(".remote")
               for d in decorator_names(node))


def _imports_of(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for alias in node.names:
                out[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return out


def _span(node: ast.AST) -> Tuple[int, int]:
    return node.lineno, getattr(node, "end_lineno", node.lineno)


class _FunctionExtractor:
    """Builds one FunctionSummary from an ast function node."""

    def __init__(self, fn: ast.AST, qualname: str, cls: str,
                 is_actor: bool, bare_gets: Dict[str, str],
                 imports: Optional[Dict[str, str]] = None):
        self.fn = fn
        self.bare_gets = bare_gets
        self.imports = imports or {}
        self.s = FunctionSummary(
            qualname=qualname, line=fn.lineno, cls=cls, is_actor=is_actor,
            is_async=isinstance(fn, ast.AsyncFunctionDef))

    def run(self) -> FunctionSummary:
        s = self.s
        rank_arm_nodes = []   # nodes already claimed by a rank branch
        for node in walk_scope(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tag = _ctor_tag(node.value)
                if tag:
                    s.local_types[node.targets[0].id] = tag
            if isinstance(node, ast.If) and mentions_rank(node.test):
                s.rank_branches.append({
                    "line": node.lineno,
                    "arms": [self._arm(node.body), self._arm(node.orelse)],
                })
                rank_arm_nodes.append(node)
            elif isinstance(node, ast.IfExp) and mentions_rank(node.test):
                s.rank_branches.append({
                    "line": node.lineno,
                    "arms": [self._arm([node.body]),
                             self._arm([node.orelse])],
                })
            elif isinstance(node, ast.With):
                self._with(node)
            elif isinstance(node, ast.Call):
                self._call(node)
        self._channel_ops()
        self._spmd()
        return s

    # -- pieces ----------------------------------------------------------
    def _arm(self, nodes) -> Dict[str, Any]:
        ops, calls = [], []
        for n in nodes:
            for sub in ast.walk(n):
                if not isinstance(sub, ast.Call):
                    continue
                op = collective_op(sub)
                if op:
                    ops.append([op, sub.lineno, sub.col_offset])
                else:
                    calls.append([dotted_name(sub.func), sub.lineno,
                                  sub.col_offset])
        return {"ops": ops, "calls": calls}

    def _call(self, node: ast.Call) -> None:
        s = self.s
        name = dotted_name(node.func)
        parts = name.split(".")
        s.calls.append([name, node.lineno, node.col_offset])
        op = collective_op(node)
        if op:
            s.collectives.append([op, node.lineno, node.col_offset])
        short = name[5:] if name.startswith("self.") else name
        if name in BLOCKING_GET or short in BLOCKING_GET \
                or (len(parts) == 1
                    and self.bare_gets.get(parts[0]) == "get"):
            s.blocking.append({
                "kind": "get", "name": name, "line": node.lineno,
                "col": node.col_offset,
                "targets": [t for a in node.args + [k.value for k in
                                                    node.keywords]
                            for t in _remote_targets(a)]})
        elif name in BLOCKING_WAIT or short in BLOCKING_WAIT \
                or (len(parts) == 1
                    and self.bare_gets.get(parts[0]) == "wait"):
            s.blocking.append({"kind": "wait", "name": name,
                               "line": node.lineno, "col": node.col_offset,
                               "targets": []})
        elif name == "time.sleep":
            secs: Optional[float] = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, (int, float)):
                secs = float(node.args[0].value)
            s.blocking.append({"kind": "sleep", "name": name,
                               "line": node.lineno, "col": node.col_offset,
                               "seconds": secs, "targets": []})
        elif parts[-1] == "join" and len(parts) >= 2 and parts[0] != "?":
            s.blocking.append({"kind": "join", "name": name,
                               "recv": ".".join(parts[:-1]),
                               "line": node.lineno, "col": node.col_offset,
                               "targets": []})
        elif parts[-1] == "wait" and len(parts) >= 2 and parts[0] != "?":
            # cond.wait() — blocking unless it is the section's own lock
            s.blocking.append({"kind": "cond-wait", "name": name,
                               "recv": ".".join(parts[:-1]),
                               "line": node.lineno, "col": node.col_offset,
                               "targets": []})
        elif parts[-1] == "acquire" and len(parts) >= 2 \
                and parts[0] != "?":
            self.s.lock_sections.append({
                "expr": ".".join(parts[:-1]), "line": node.lineno,
                "col": node.col_offset, "span": [node.lineno, node.lineno],
                "acquire_only": True})

    def _with(self, node: ast.With) -> None:
        body_start = node.body[0].lineno if node.body else node.lineno
        group = id(node) & 0xFFFFFFFF
        for gi, item in enumerate(node.items):
            expr = item.context_expr
            if isinstance(expr, (ast.Name, ast.Attribute)):
                name = dotted_name(expr)
                if name.startswith("?"):
                    continue
                self.s.lock_sections.append({
                    "expr": name, "line": node.lineno,
                    "col": node.col_offset,
                    "span": [body_start, _span(node)[1]],
                    "acquire_only": False, "group": group,
                    "group_idx": gi})

    def _channel_ops(self) -> None:
        """Ordered channel ops with (block, idx) identity so protocol
        rules can reason about straight-line statement order."""
        block_counter = [0]
        BLOCK_ATTRS = ("body", "orelse", "finalbody")

        def header_calls(stmt):
            """Calls in a statement outside its nested blocks/scopes."""
            skip = set()
            for attr in BLOCK_ATTRS:
                for s in getattr(stmt, attr, None) or ():
                    skip.add(id(s))
            for h in getattr(stmt, "handlers", None) or ():
                skip.add(id(h))
            stack = [c for c in ast.iter_child_nodes(stmt)
                     if id(c) not in skip]
            while stack:
                n = stack.pop()
                if isinstance(n, FuncNode + (ast.Lambda,)):
                    continue
                if isinstance(n, ast.Call):
                    yield n
                stack.extend(ast.iter_child_nodes(n))

        def visit_block(stmts) -> None:
            block_counter[0] += 1
            bid = block_counter[0]
            for idx, stmt in enumerate(stmts):
                for sub in header_calls(stmt):
                    name = dotted_name(sub.func)
                    parts = name.split(".")
                    if len(parts) >= 2 and parts[-1] in CHANNEL_OPS \
                            and parts[0] != "?":
                        self.s.channel_ops.append({
                            "recv": ".".join(parts[:-1]),
                            "op": parts[-1], "line": sub.lineno,
                            "col": sub.col_offset, "block": bid,
                            "idx": idx})
                for attr in BLOCK_ATTRS:
                    sub_stmts = getattr(stmt, attr, None)
                    if sub_stmts:
                        visit_block(sub_stmts)
                for h in getattr(stmt, "handlers", None) or ():
                    visit_block(h.body)

        visit_block(self.fn.body)

    # -- SPMD plane ------------------------------------------------------
    def _spmd(self) -> None:
        """Populate FunctionSummary.spmd. Runs its own ordered traversal:
        the main walk is BFS (ast.walk) which scrambles statement order,
        and collective schedules are only meaningful linearized."""
        fn, s = self.fn, self.s
        sp: Dict[str, Any] = {}
        a = fn.args
        pos = [p.arg for p in list(getattr(a, "posonlyargs", [])) + a.args]
        sp["params"] = [len(pos), len(pos) - len(a.defaults),
                        1 if a.vararg else 0, pos[0] if pos else ""]
        sp["returns"] = _returns_arity(fn)
        jd = jit_decorator(fn)
        if jd:
            sp["jit"] = jd

        uses: List[List[Any]] = []
        decls: List[List[Any]] = []
        wraps: List[List[Any]] = []
        groups: List[List[Any]] = []
        effects: List[List[Any]] = []
        # def f(..., axis_name="sp"): the default is an axis use too
        for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
            if p in ("axis_name", "axis_names") and d is not None:
                for ax in _axis_strs(d):
                    uses.append([ax, d.lineno, d.col_offset,
                                 "axis-default"])

        claimed: set = set()      # call nodes owned by rank-branch arms
        rank_scheds: List[Dict[str, Any]] = []
        for node in walk_scope(fn):
            is_rank_if = isinstance(node, ast.If) \
                and mentions_rank(node.test)
            is_rank_ifexp = isinstance(node, ast.IfExp) \
                and mentions_rank(node.test)
            if not (is_rank_if or is_rank_ifexp):
                continue
            parts = ([node.body, node.orelse] if is_rank_if
                     else [[node.body], [node.orelse]])
            arms = []
            for arm in parts:
                arm_calls = [c for st in arm for c in ast.walk(st)
                             if isinstance(c, ast.Call)]
                claimed.update(id(c) for c in arm_calls)
                arms.append(self._events(arm_calls))
            rank_scheds.append({"line": node.lineno, "arms": arms})

        all_calls = [n for n in walk_scope(fn)
                     if isinstance(n, ast.Call)]
        all_calls.sort(key=lambda c: (c.lineno, c.col_offset))
        for c in all_calls:
            self._spmd_call(c, uses, decls, wraps, groups, effects)
        schedule = self._events([c for c in all_calls
                                 if id(c) not in claimed])

        if uses:
            sp["axis_uses"] = uses
        if decls:
            sp["axis_decls"] = decls
        if wraps:
            sp["jit_wraps"] = wraps
        if groups:
            sp["group_literals"] = groups
        if effects:
            sp["host_effects"] = effects
        if schedule:
            sp["schedule"] = schedule
        if rank_scheds:
            sp["rank_scheds"] = rank_scheds
        s.spmd = sp

    def _events(self, calls: List[ast.Call]) -> List[List[Any]]:
        out = []
        for c in sorted(calls, key=lambda c: (c.lineno, c.col_offset)):
            ev = self._event_for(c)
            if ev:
                out.append(ev)
        return out

    def _event_for(self, call: ast.Call) -> Optional[List[Any]]:
        name = dotted_name(call.func)
        op = collective_op(call)
        if op:
            return ["op", op, self._group_of(call, op),
                    call.lineno, call.col_offset]
        lax = self._lax_axes(call, name)
        if lax is not None:
            kind, axes = lax
            return ["op", kind, ",".join(axes),
                    call.lineno, call.col_offset]
        if "?" in name:
            return None
        return ["call", name, call.lineno, call.col_offset]

    def _lax_axes(self, call: ast.Call,
                  name: str) -> Optional[Tuple[str, List[str]]]:
        """(op, literal axes) when this is a jax.lax device collective,
        else None. Bare names must be imported from jax.lax."""
        parts = name.split(".")
        tail = parts[-1]
        if tail not in LAX_COLLECTIVES:
            return None
        if len(parts) > 1:
            if "lax" not in parts[:-1]:
                return None
        elif "jax.lax" not in self.imports.get(tail, ""):
            return None
        node = _kwarg(call, "axis_name")
        if node is None and len(call.args) > 1:
            node = call.args[1]
        return tail, _axis_strs(node)

    def _group_of(self, call: ast.Call, op: str) -> str:
        """Literal group name on a host-collective call, '' if dynamic."""
        node = _kwarg(call, "group_name")
        if node is None:
            idx = HOST_GROUP_ARG.get(op, -1)
            if 0 <= idx < len(call.args):
                node = call.args[idx]
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return ""

    def _collectiveish(self, parts: List[str], tail: str) -> bool:
        if len(parts) == 1:
            return tail in COLLECTIVE_OPS \
                or "collective" in self.imports.get(tail, "")
        return any(w in p for p in parts[:-1]
                   for w in _COLLECTIVE_RECEIVERS)

    def _spmd_call(self, call: ast.Call, uses, decls, wraps, groups,
                   effects) -> None:
        name = dotted_name(call.func)
        parts = name.split(".")
        tail = parts[-1]
        ln, col = call.lineno, call.col_offset

        # PartitionSpec("dp", ...) — bare aliases (P) resolve via imports
        full = self.imports.get(tail, "") if len(parts) == 1 else name
        if tail == "PartitionSpec" or full.endswith(".PartitionSpec"):
            for argn in list(call.args) + [k.value for k in call.keywords]:
                for ax in _axis_strs(argn):
                    uses.append([ax, ln, col, "partition-spec"])

        # axis_name=/axis_names= kwargs anywhere
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                for ax in _axis_strs(kw.value):
                    uses.append([ax, ln, col, "axis-kwarg"])

        # lax collectives / axis queries with a positional axis arg
        if self._lax_axes(call, name) is not None:
            if _kwarg(call, "axis_name") is None and len(call.args) > 1:
                for ax in _axis_strs(call.args[1]):
                    uses.append([ax, ln, col, "lax-collective"])
        elif tail in LAX_AXIS_QUERIES \
                and ("lax" in parts[:-1] or "jax" in parts[:-1]
                     or (len(parts) == 1
                         and "jax" in self.imports.get(tail, ""))):
            if _kwarg(call, "axis_name") is None and call.args:
                for ax in _axis_strs(call.args[0]):
                    uses.append([ax, ln, col, "axis-query"])

        # ShardingRules mesh-axis values: .with_(embed="fsdp") kwarg
        # values, and the (("logical", ("mesh", ...)), ...) rule tables
        if tail == "with_":
            for kw in call.keywords:
                for ax in _axis_strs(kw.value):
                    uses.append([ax, ln, col, "rules-value"])
        elif tail == "ShardingRules" or (tail == "cls"
                                         and self.s.cls == "ShardingRules"):
            for argn in call.args:
                if isinstance(argn, (ast.Tuple, ast.List)):
                    for e in argn.elts:
                        if isinstance(e, (ast.Tuple, ast.List)) \
                                and len(e.elts) == 2:
                            for ax in _axis_strs(e.elts[1]):
                                uses.append([ax, ln, col, "rules-value"])

        # mesh constructions declare axes
        if tail in ("MeshSpec", "DCNSpec"):
            for kw in call.keywords:
                if kw.arg:
                    decls.append([kw.arg, ln])
        elif tail in ("Mesh", "make_mesh"):
            node = _kwarg(call, "axis_names")
            if node is None and len(call.args) > 1:
                node = call.args[1]
            for ax in _axis_strs(node):
                decls.append([ax, ln])

        # jit wrap call sites: jax.jit(f) / shard_map(f, ...) /
        # sharded_jit(f, ...) with a resolvable target
        wrap_kind = ""
        if tail == "shard_map" \
                and ("jax" in parts[:-1]
                     or (len(parts) == 1
                         and "shard_map" in self.imports.get(tail, ""))):
            wrap_kind = "shard_map"
        elif tail == "jit" \
                and ("jax" in parts[:-1]
                     or (len(parts) == 1
                         and self.imports.get(tail, "") == "jax.jit")):
            wrap_kind = "jit"
        elif tail == "sharded_jit":
            wrap_kind = "sharded_jit"
        if wrap_kind and call.args:
            target = call.args[0]
            if isinstance(target, (ast.Name, ast.Attribute)):
                tname = dotted_name(target)
                if "?" not in tname:
                    wraps.append([wrap_kind, tname, ln,
                                  _spec_arity(_kwarg(call, "in_specs")),
                                  _spec_arity(_kwarg(call, "out_specs"))])

        # hardcoded group names on host-collective calls
        if tail in HOST_GROUP_ARG and self._collectiveish(parts, tail):
            g = self._group_of(call, tail)
            if g:
                groups.append([tail, g, ln, col])

        # host effects: wall-clock reads and metric RPCs
        short = name[5:] if name.startswith("self.") else name
        if name in WALL_CLOCK or short in WALL_CLOCK \
                or (len(parts) == 1
                    and self.imports.get(tail, "") in WALL_CLOCK):
            effects.append(["wall-clock", name, ln, col])
        elif tail in ("inc", "observe", "set") and len(parts) >= 2 \
                and any(w in p.lower() for p in parts[:-1]
                        for w in _METRIC_RECV_WORDS):
            effects.append(["metric", name, ln, col])


def _class_summary(node: ast.ClassDef, module: str) -> ClassSummary:
    cs = ClassSummary(name=node.name, line=node.lineno,
                      is_actor=_is_actor_class(node),
                      bases=[dotted_name(b).split(".")[-1]
                             for b in node.bases])
    for st in node.body:
        if isinstance(st, FuncNode):
            cs.methods.append(st.name)
        elif isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    tag = _ctor_tag(st.value)
                    if tag:
                        cs.attr_types[t.id] = tag
                        cs.attr_lines[t.id] = st.lineno
    # self.X = <ctor> anywhere in the class's methods
    for fn in node.body:
        if not isinstance(fn, FuncNode):
            continue
        for sub in walk_scope(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    tag = _ctor_tag(sub.value)
                    if tag and t.attr not in cs.attr_types:
                        cs.attr_types[t.attr] = tag
                        cs.attr_lines[t.attr] = sub.lineno
    return cs


def summarize(tree: ast.Module, source: str, path: str) -> FileSummary:
    """The per-file half of the interprocedural analysis; pure function
    of the file content, which is what makes it cacheable."""
    module = module_name_for(path)
    fs = FileSummary(path=path, module=module)
    fs.imports = _imports_of(tree)
    bare_gets = {local: target.rsplit(".", 1)[1]
                 for local, target in fs.imports.items()
                 if target in ("ray_tpu.get", "ray_tpu.wait")}

    for node in tree.body:
        targets: List[ast.Name] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
            for t in targets:
                tag = _ctor_tag(node.value)
                if tag:
                    fs.module_types[t.id] = tag
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            # AXIS_ORDER: Tuple[str, ...] = ("dp", ...) is an AnnAssign
            targets, value = [node.target], node.value
        for t in targets:
            if ("axis" in t.id.lower() or "axes" in t.id.lower()) \
                    and isinstance(value, (ast.Tuple, ast.List)):
                for ax in _axis_strs(value):
                    fs.spmd.setdefault("axis_decls", []).append(
                        [ax, node.lineno])

    # parent map for qualnames
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node

    def qualname_of(fn: ast.AST) -> Tuple[str, str, bool]:
        names: List[str] = [fn.name]
        cls, is_actor = "", False
        cur = parents.get(id(fn))
        while cur is not None and not isinstance(cur, ast.Module):
            if isinstance(cur, ast.ClassDef):
                if not cls:
                    cls, is_actor = cur.name, _is_actor_class(cur)
                names.append(cur.name)
            elif isinstance(cur, FuncNode):
                names.append(cur.name)
            cur = parents.get(id(cur))
        return ".".join(reversed(names)), cls, is_actor

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            fs.classes.append(_class_summary(node, module))
        elif isinstance(node, FuncNode):
            qn, cls, is_actor = qualname_of(node)
            fs.functions.append(_FunctionExtractor(
                node, qn, cls, is_actor, bare_gets, fs.imports).run())

    from ray_tpu.devtools.lint.rules.config_drift import extract_config
    fs.config = extract_config(tree, source, path)
    return fs
