"""Inline suppression parsing.

Syntax (mirrors pylint's, namespaced so the two coexist):

    x.remote(payload)  # raylint: disable=leaked-object-ref  -- fire&forget push

suppresses the named rule(s) on that line. A comment-only line
suppresses the line *below* it (for statements too long to share a line
with their justification):

    # raylint: disable=divergent-collective -- root-only barrier by design
    collective.barrier()

`disable=all` suppresses every rule on the line. A file-level opt-out

    # raylint: disable-file=large-closure-capture

anywhere in the file suppresses that rule for the whole file (reserved
for generated or fixture code; real code should suppress per-line with a
justification).
"""

from __future__ import annotations

import re
from typing import Dict, Set

_RULE_LIST = r"([\w-]+(?:\s*,\s*[\w-]+)*)"
_LINE_RE = re.compile(r"#\s*raylint:\s*disable=" + _RULE_LIST)
_FILE_RE = re.compile(r"#\s*raylint:\s*disable-file=" + _RULE_LIST)
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


def _rules_of(match: re.Match) -> Set[str]:
    return {r.strip() for r in match.group(1).split(",") if r.strip()}


class Suppressions:
    """Per-file suppression table, queried by (rule, line)."""

    def __init__(self, source: str):
        self.by_line: Dict[int, Set[str]] = {}
        self.file_level: Set[str] = set()
        for i, text in enumerate(source.splitlines(), start=1):
            m = _FILE_RE.search(text)
            if m:
                self.file_level |= _rules_of(m)
                continue
            m = _LINE_RE.search(text)
            if not m:
                continue
            rules = _rules_of(m)
            self.by_line.setdefault(i, set()).update(rules)
            if _COMMENT_ONLY_RE.match(text):
                # comment-only directive also covers the next line
                self.by_line.setdefault(i + 1, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_level or "all" in self.file_level:
            return True
        rules = self.by_line.get(line, ())
        return rule in rules or "all" in rules
