"""Inline suppression parsing.

Syntax (mirrors pylint's, namespaced so the two coexist):

    x.remote(payload)  # raylint: disable=leaked-object-ref  -- fire&forget push

suppresses the named rule(s) on that line. A comment-only line
suppresses the line *below* it (for statements too long to share a line
with their justification):

    # raylint: disable=divergent-collective -- root-only barrier by design
    collective.barrier()

`disable=all` suppresses every rule on the line. A file-level opt-out

    # raylint: disable-file=large-closure-capture

anywhere in the file suppresses that rule for the whole file (reserved
for generated or fixture code; real code should suppress per-line with a
justification).

Every directive is kept in ``directives`` with the lines it covers, so
the useless-suppression meta-rule can audit the inventory: a directive
whose rule never fires at a covered line is itself a finding.

Directives are recognized in real comments only (tokenize-level), never
inside string literals — this file's own docstring examples must not
suppress anything, and before the tokenizer pass they did: the
``disable-file=large-closure-capture`` example above silently opted
this whole file out of that rule.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, List, Set

_RULE_LIST = r"([\w-]+(?:\s*,\s*[\w-]+)*)"
_LINE_RE = re.compile(r"#\s*raylint:\s*disable=" + _RULE_LIST)
_FILE_RE = re.compile(r"#\s*raylint:\s*disable-file=" + _RULE_LIST)


def _rules_of(match: re.Match) -> Set[str]:
    return {r.strip() for r in match.group(1).split(",") if r.strip()}


def _comments(source: str):
    """(line, text, own_line) for each real comment token. Falls back to
    nothing on tokenize errors — the file already failed to parse and is
    reported as a parse error, so losing its directives is moot."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                own_line = tok.line[:tok.start[1]].strip() == ""
                yield tok.start[0], tok.string, own_line
    except (tokenize.TokenizeError, SyntaxError, ValueError, IndentationError):
        return


class Suppressions:
    """Per-file suppression table, queried by (rule, line)."""

    def __init__(self, source: str):
        self.by_line: Dict[int, Set[str]] = {}
        self.file_level: Set[str] = set()
        # [{"line", "rules", "file_level", "covered"}] for auditing
        self.directives: List[dict] = []
        for i, text, own_line in _comments(source):
            m = _FILE_RE.search(text)
            if m:
                rules = _rules_of(m)
                self.file_level |= rules
                self.directives.append({"line": i, "rules": rules,
                                        "file_level": True, "covered": []})
                continue
            m = _LINE_RE.search(text)
            if not m:
                continue
            rules = _rules_of(m)
            covered = [i]
            self.by_line.setdefault(i, set()).update(rules)
            if own_line:
                # comment-only directive also covers the next line
                self.by_line.setdefault(i + 1, set()).update(rules)
                covered.append(i + 1)
            self.directives.append({"line": i, "rules": rules,
                                    "file_level": False,
                                    "covered": covered})

    def is_suppressed(self, rule: str, line: int,
                      file_only: bool = False) -> bool:
        """``file_only`` restricts to disable-file= directives (rules
        with ``file_wide_only = True``, e.g. useless-suppression —
        otherwise a line-level disable could hide its own audit)."""
        if rule in self.file_level or "all" in self.file_level:
            return True
        if file_only:
            return False
        rules = self.by_line.get(line, ())
        return rule in rules or "all" in rules
