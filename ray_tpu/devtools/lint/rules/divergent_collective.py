"""divergent-collective: collective op call under a rank conditional.

Collective operations (allreduce/allgather/broadcast/reducescatter/
barrier) are rendezvous points: every rank in the group must reach the
same call in the same order, or the ranks that did call it block until
the per-round timeout fires and the whole slice aborts. ``if rank ==
0: broadcast(...)`` is the canonical deadlock — broadcast is collective
even for the source rank.

Flags calls whose callee is a known collective op when the call sits in
an ``if``/ternary whose test mentions a rank-ish name AND the same op
is not also called in the opposite branch (``broadcast(x) if rank == 0
else broadcast(None)`` is convergent: every rank still makes the
call). Matches bare names (``from ray_tpu.collective import barrier``)
and dotted calls through a collective-ish receiver
(``collective.barrier``, ``col.allreduce``, ``self.group.barrier``).
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from ray_tpu.devtools.lint.astutil import dotted_name
from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

_OPS = {
    "allreduce", "allgather", "broadcast", "reducescatter", "barrier",
    "allreduce_async", "allgather_async", "broadcast_async",
    "reducescatter_async", "barrier_async",
}
_RECEIVER_WORDS = ("collective", "col", "group", "comm")
_RANK_WORDS = ("rank", "is_leader", "is_root", "is_coordinator")


def _collective_op(call: ast.Call) -> str:
    """The op name if this is a collective call, else ''."""
    name = dotted_name(call.func)
    parts = name.split(".")
    if parts[-1] not in _OPS:
        return ""
    if len(parts) > 1 and not any(w in p for p in parts[:-1]
                                  for w in _RECEIVER_WORDS):
        return ""
    return parts[-1]


def _mentions_rank(test: ast.AST) -> bool:
    for node in ast.walk(test):
        word = None
        if isinstance(node, ast.Name):
            word = node.id
        elif isinstance(node, ast.Attribute):
            word = node.attr
        if word and any(w in word.lower() for w in _RANK_WORDS):
            return True
    return False


def _branch_calls(nodes) -> List[Tuple[str, ast.Call]]:
    out = []
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call):
                op = _collective_op(sub)
                if op:
                    out.append((op, sub))
    return out


@register
class DivergentCollective(Rule):
    id = "divergent-collective"
    doc = ("collective op called in one arm of an `if rank...` branch — "
           "ranks that skip the call deadlock the group")
    hint = ("hoist the collective out of the conditional (all ranks call "
            "it); branch on rank only around the non-collective work")

    def check(self, parsed):
        seen: Set[int] = set()
        for node in ast.walk(parsed.tree):
            if isinstance(node, ast.If) and _mentions_rank(node.test):
                body, orelse = _branch_calls(node.body), \
                    _branch_calls(node.orelse)
            elif isinstance(node, ast.IfExp) and _mentions_rank(node.test):
                body, orelse = _branch_calls([node.body]), \
                    _branch_calls([node.orelse])
            else:
                continue
            body_ops = {op for op, _ in body}
            else_ops = {op for op, _ in orelse}
            for op, call in body + orelse:
                if op in body_ops and op in else_ops:
                    continue  # convergent: both arms make the call
                if id(call) in seen:
                    continue
                seen.add(id(call))
                yield Finding(
                    rule=self.id, path=parsed.path,
                    line=call.lineno, col=call.col_offset,
                    message=f"collective {dotted_name(call.func)}(...) "
                            "inside a rank-dependent branch — ranks not "
                            "taking this branch deadlock the group",
                    hint=self.hint)
