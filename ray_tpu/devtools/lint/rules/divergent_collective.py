"""divergent-collective: collective op call under a rank conditional.

Collective operations (allreduce/allgather/broadcast/reducescatter/
barrier) are rendezvous points: every rank in the group must reach the
same call in the same order, or the ranks that did call it block until
the per-round timeout fires and the whole slice aborts. ``if rank ==
0: broadcast(...)`` is the canonical deadlock — broadcast is collective
even for the source rank.

Interprocedural since the raylint call-graph engine landed: a helper
that hides the collective no longer hides the hazard —

    if rank == 0:
        _sync_weights(model)      # _sync_weights allreduces inside

is flagged at the call site, with the path to the buried collective.
The convergence check is symmetric: an op invoked (directly or through
helpers) in *both* arms is a rendezvous every rank still reaches, so
``broadcast(x) if rank == 0 else broadcast(None)`` stays clean even
when one side routes through a wrapper.

Matches bare names (``from ray_tpu.collective import barrier``) and
dotted calls through a collective-ish receiver (``collective.barrier``,
``col.allreduce``, ``self.group.barrier``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register


def _arm_ops(graph, module: str, cls: str, arm: dict,
             cache: Dict[str, Dict[str, tuple]]
             ) -> Dict[str, List[tuple]]:
    """{op: [(line, col, via)]} for one branch arm: direct collective
    calls plus collectives reachable through resolvable helper calls."""
    out: Dict[str, List[tuple]] = {}
    for op, line, col in arm["ops"]:
        out.setdefault(op, []).append((line, col, ""))
    for name, line, col in arm["calls"]:
        callee = graph.resolve_call(module, cls, name)
        if callee is None:
            continue
        if callee not in cache:
            cache[callee] = graph.collectives_reachable(callee)
        for op, (nid, path, site) in cache[callee].items():
            owner = graph.summary(nid)
            chain = " -> ".join(
                [name] + [p[0] for p in path]
                + ([owner.qualname] if owner is not None and path == []
                   and nid != callee else []))
            out.setdefault(op, []).append((line, col, chain))
    return out


@register
class DivergentCollective(Rule):
    id = "divergent-collective"
    doc = ("collective op called in one arm of an `if rank...` branch — "
           "ranks that skip the call deadlock the group (helpers are "
           "followed through the call graph)")
    hint = ("hoist the collective out of the conditional (all ranks call "
            "it); branch on rank only around the non-collective work")
    scope = "graph"

    def check_graph(self, graph):
        cache: Dict[str, Dict[str, tuple]] = {}
        for nid, s in sorted(graph.functions.items()):
            module = nid.split(":", 1)[0]
            path = graph.fn_path.get(nid, "?")
            for br in s.rank_branches:
                arms = [_arm_ops(graph, module, s.cls, arm, cache)
                        for arm in br["arms"]]
                body_ops, else_ops = set(arms[0]), set(arms[1])
                seen: Set[Tuple[int, int]] = set()
                for arm_ops in arms:
                    for op, sites in sorted(arm_ops.items()):
                        if op in body_ops and op in else_ops:
                            continue   # convergent: both arms reach it
                        for line, col, via in sites:
                            if (line, col) in seen:
                                continue
                            seen.add((line, col))
                            where = (f"collective {op}(...)"
                                     if not via else
                                     f"call reaching collective {op} "
                                     f"({via})")
                            yield Finding(
                                rule=self.id, path=path, line=line,
                                col=col,
                                message=(f"{where} inside a "
                                         "rank-dependent branch — ranks "
                                         "not taking this branch "
                                         "deadlock the group"),
                                hint=self.hint)
