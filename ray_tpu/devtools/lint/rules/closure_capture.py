"""large-closure-capture: remote fns closing over module-level arrays.

A remote function (or actor method) that references a module-level
ndarray / jnp constant serializes that array into the function's
closure, shipping it with EVERY task submission — and for device arrays
forces a D2H copy per pickle. The fix is to ``put()`` the array once
and pass the ref, pass it as an argument, or construct it inside the
task.

Detection is two-phase per file: collect module-level names assigned
from numpy/jax array factories, then flag Name loads of those inside
``@remote``-decorated functions and methods of ``@remote`` classes.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.astutil import (FuncNode, dotted_name,
                                           is_remote_decorated, walk_scope)
from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

_ARRAY_ROOTS = {"np", "jnp", "numpy", "jax"}
_FACTORIES = {
    "array", "asarray", "ones", "zeros", "full", "empty", "arange",
    "linspace", "eye", "identity", "rand", "randn", "normal", "uniform",
    "randint", "ones_like", "zeros_like", "full_like", "load", "loadtxt",
}


def _is_array_expr(value: ast.AST) -> bool:
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            parts = name.split(".")
            if parts[0] in _ARRAY_ROOTS and parts[-1] in _FACTORIES:
                return True
    return False


def _module_array_consts(tree: ast.Module) -> dict:
    consts = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) and node.value:
            target, value = node.target.id, node.value
        else:
            continue
        if _is_array_expr(value):
            consts[target] = node.lineno
    return consts


@register
class LargeClosureCapture(Rule):
    id = "large-closure-capture"
    doc = ("remote fn/actor method closes over a module-level ndarray — "
           "the array is reserialized into every task submission")
    hint = ("put() the array once and pass the ObjectRef, pass it as an "
            "argument, or build it inside the task")

    def check(self, parsed):
        consts = _module_array_consts(parsed.tree)
        if not consts:
            return
        remote_fns = []
        for node in ast.walk(parsed.tree):
            if isinstance(node, FuncNode) and is_remote_decorated(node):
                remote_fns.append(node)
            elif isinstance(node, ast.ClassDef) \
                    and is_remote_decorated(node):
                remote_fns.extend(n for n in node.body
                                  if isinstance(n, FuncNode))
        for fn in remote_fns:
            # shadowed names are the function's own, not captures
            local = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
            for sub in walk_scope(fn):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            local.add(t.id)
            for sub in walk_scope(fn):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load) \
                        and sub.id in consts and sub.id not in local:
                    yield Finding(
                        rule=self.id, path=parsed.path,
                        line=sub.lineno, col=sub.col_offset,
                        message=f"remote {fn.name} captures module-level "
                                f"array {sub.id!r} (defined line "
                                f"{consts[sub.id]}) in its closure — "
                                "serialized per task",
                        hint=self.hint)
