"""collective-schedule-divergence: rank arms order collectives alike.

The existing divergent-collective rule is set-based: an op reached from
*both* arms of an ``if rank...`` branch is convergent and stays clean.
That misses the ordering deadlock — two arms that each reach the same
rendezvous set but in a different order:

    if rank == 0:
        col.allreduce(g, "grads")   # rank 0 waits in allreduce...
        col.barrier("grads")
    else:
        col.barrier("grads")        # ...while everyone else waits in
        col.allreduce(g, "grads")   # barrier. Nobody moves.

This rule linearizes each arm's collective schedule — host collectives
*and* lax device collectives, with resolvable helper calls inlined
through the project call graph — and requires the (op, axis/group)
token sequences to agree. It fires only when the arms' op-kind sets
already match (otherwise divergent-collective owns the finding), so
the two rules partition the failure space instead of double-reporting.
"""

from __future__ import annotations

from typing import List, Tuple

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register


def _render(sched: List[Tuple[str, str]]) -> str:
    if not sched:
        return "(no collectives)"
    return " -> ".join(f"{op}[{ax}]" if ax else op for op, ax in sched)


@register
class CollectiveScheduleDivergence(Rule):
    id = "collective-schedule-divergence"
    doc = ("rank-conditional arms issue the same collectives in a "
           "different order (or against different axes/groups) — every "
           "rank blocks in a different rendezvous and the group wedges")
    hint = ("make both arms issue collectives in one order — hoist the "
            "shared tail out of the conditional, or reorder one arm")
    scope = "graph"

    def check_graph(self, graph):
        for nid, s in sorted(graph.functions.items()):
            module = nid.split(":", 1)[0]
            path = graph.fn_path.get(nid, "?")
            for br in (s.spmd or {}).get("rank_scheds", []):
                arms = [graph.linearize_events(module, s.cls, a)
                        for a in br["arms"]]
                a, b = arms
                if a == b:
                    continue
                # different op-kind sets: divergent-collective territory
                if {op for op, _ in a} != {op for op, _ in b}:
                    continue
                yield Finding(
                    rule=self.id, path=path, line=br["line"], col=0,
                    message=("rank arms disagree on collective order: "
                             f"the true arm runs {_render(a)} but the "
                             f"other arm runs {_render(b)} — same "
                             "rendezvous set, different order, so each "
                             "rank blocks in a different collective"),
                    hint=self.hint,
                    spmd={"schedule_true": [list(t) for t in a],
                          "schedule_false": [list(t) for t in b]})
