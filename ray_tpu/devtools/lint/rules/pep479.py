"""pep479-stopiteration: StopIteration escaping a generator body.

Since PEP 479 (Python 3.7), a StopIteration raised inside a generator —
whether explicitly or by an unguarded ``next()`` — is converted to
RuntimeError instead of ending iteration. The PR-1 collective broadcast
bug was exactly this: a bare ``next()`` over ragged per-rank iterators
took down the whole broadcast with RuntimeError when one rank drained
early.

Flags, inside generator functions only:
- ``raise StopIteration``: always wrong; ``return`` ends a generator.
- single-argument ``next(it)`` not wrapped in a ``try`` that catches
  StopIteration (two-arg ``next(it, default)`` never raises).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.astutil import (catches, enclosing_stack,
                                           is_generator, walk_scope)
from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register


def _guarded(tree: ast.AST, fn: ast.AST, call: ast.Call) -> bool:
    """True if ``call`` sits in a try whose handlers catch StopIteration
    (within the generator's own scope — an outer try can't help)."""
    stack = enclosing_stack(tree, call)
    if fn in stack:
        stack = stack[stack.index(fn) + 1:]
    for anc in stack:
        if isinstance(anc, ast.Try):
            if any(catches(h, "StopIteration") for h in anc.handlers):
                return True
    return False


@register
class Pep479StopIteration(Rule):
    id = "pep479-stopiteration"
    doc = ("bare next()/raise StopIteration inside a generator becomes "
           "RuntimeError under PEP 479")
    hint = ("use `return` to end the generator; wrap next() in "
            "try/except StopIteration or pass a default")

    def check(self, parsed):
        for fn in ast.walk(parsed.tree):
            if not is_generator(fn):
                continue
            for node in walk_scope(fn):
                if isinstance(node, ast.Raise) and node.exc is not None:
                    exc = node.exc
                    name = exc.func if isinstance(exc, ast.Call) else exc
                    if isinstance(name, ast.Name) and \
                            name.id == "StopIteration":
                        yield Finding(
                            rule=self.id, path=parsed.path,
                            line=node.lineno, col=node.col_offset,
                            message=f"raise StopIteration inside generator "
                                    f"{fn.name} becomes RuntimeError "
                                    "(PEP 479)",
                            hint="use a plain `return` to end the generator")
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Name)
                      and node.func.id == "next"
                      and len(node.args) == 1 and not node.keywords
                      and not _guarded(parsed.tree, fn, node)):
                    yield Finding(
                        rule=self.id, path=parsed.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"unguarded next() inside generator "
                                f"{fn.name}: an exhausted iterator raises "
                                "StopIteration -> RuntimeError (PEP 479)",
                        hint="wrap in try/except StopIteration, or use "
                             "next(it, sentinel)")
