"""unbounded-rpc-call: control-plane RPCs opted out of the deadline.

Every ``RpcClient.call`` carries ``rpc_call_timeout_s`` by default
(core/rpc.py sentinel), so the only way to hang forever on a gray peer —
black-holed link, wedged handler — is to pass an explicit
``timeout=None``. That opt-out is legitimate exactly twice in the tree
(task pushes, whose awaits are bounded by connection liveness via the
keepalive, not by a deadline) and each such site must carry a reviewed
``# raylint: disable=unbounded-rpc-call`` justification. Anything else
is a partition hazard: the caller blocks past every failure-detection
bound the health plane has.

Matched shape: a call whose callee attribute is ``call`` or
``start_call`` with an explicit ``timeout=None`` keyword. Methods named
``call`` on non-RPC objects don't pass ``timeout=None`` in this tree;
if one ever does, the suppression comment is the documented escape.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.astutil import dotted_name
from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

_RPC_METHODS = {"call", "start_call"}


@register
class UnboundedRpcCall(Rule):
    id = "unbounded-rpc-call"
    doc = ("RPC .call(..., timeout=None) opts out of the default "
           "deadline and can hang forever on a gray (black-holed) peer")
    hint = ("drop timeout=None to inherit rpc_call_timeout_s, pass an "
            "explicit bound, or justify the unbounded await with "
            "# raylint: disable=unbounded-rpc-call")

    def check(self, parsed):
        for node in ast.walk(parsed.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _RPC_METHODS:
                continue
            for kw in node.keywords:
                if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is None:
                    recv = dotted_name(node.func.value) or "<expr>"
                    yield Finding(
                        rule=self.id, path=parsed.path,
                        line=kw.value.lineno, col=kw.value.col_offset,
                        message=f"{recv}.{node.func.attr}(..., timeout=None) "
                                "is unbounded: a black-holed peer hangs this "
                                "await past every deadline",
                        hint=self.hint)
