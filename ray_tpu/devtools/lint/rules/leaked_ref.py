"""leaked-object-ref: ``.remote()`` result discarded.

A discarded ObjectRef means task failures are silently swallowed (the
error lives in the ref nobody will get()) and, under reference-counted
stores, the result object may be collected before anyone can read it.
Fire-and-forget call sites that are genuinely intentional must say so
with a suppression + one-line justification.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.astutil import dotted_name
from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register


@register
class LeakedObjectRef(Rule):
    id = "leaked-object-ref"
    doc = (".remote() called as a bare statement — the returned "
           "ObjectRef (and any error inside it) is dropped")
    hint = ("assign the ref and get()/wait() it (batch refs if needed); "
            "if fire-and-forget is intended, suppress with a justification")

    def check(self, parsed):
        for node in ast.walk(parsed.tree):
            if not isinstance(node, ast.Expr):
                continue
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if not isinstance(value, ast.Call):
                continue
            name = dotted_name(value.func)
            if name == "remote" or name.endswith(".remote"):
                yield Finding(
                    rule=self.id, path=parsed.path,
                    line=value.lineno, col=value.col_offset,
                    message=f"result of {name}(...) is discarded; task "
                            "errors will never surface",
                    hint=self.hint)
