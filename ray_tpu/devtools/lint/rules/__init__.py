"""Rule modules. Importing this package registers every rule."""

from ray_tpu.devtools.lint.rules import (actor_get_cycle,  # noqa: F401
                                         blocking_async,
                                         channel_protocol,
                                         closure_capture, config_drift,
                                         divergent_collective, leaked_ref,
                                         locks, pep479,
                                         unbounded_rpc,
                                         useless_suppression)
