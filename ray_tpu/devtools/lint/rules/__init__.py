"""Rule modules. Importing this package registers every rule."""

from ray_tpu.devtools.lint.rules import (blocking_async,  # noqa: F401
                                         closure_capture, config_drift,
                                         divergent_collective, leaked_ref,
                                         pep479)
