"""Rule modules. Importing this package registers every rule."""

from ray_tpu.devtools.lint.rules import (actor_get_cycle,  # noqa: F401
                                         blocking_async,
                                         channel_protocol,
                                         closure_capture, config_drift,
                                         divergent_collective,
                                         group_names, host_effect_jit,
                                         leaked_ref,
                                         locks, mesh_axes, pep479,
                                         schedule_divergence, spec_arity,
                                         unbounded_rpc,
                                         useless_suppression)
