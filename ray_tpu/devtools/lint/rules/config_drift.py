"""config-knob-drift: Config/DataContext knobs that drift from reality.

Two failure directions, both seen in practice:

- **unknown knob**: code reads ``cfg.some_knob`` that the Config
  dataclass never defines — silently AttributeErrors at runtime (or
  worse, a typo reads a different knob than the one being tuned).
- **dead knob**: a knob is defined (and documented, and env-var
  plumbed) but nothing ever reads it — operators tune it and nothing
  happens.

``cfg`` is a heavily overloaded name in this codebase (RL configs,
model configs...), so receiver matching is evidence-based, not
name-based: an expression is Config-typed only if it traces to a
``Config(...)``/``Config.load(...)``/``Config.from_json(...)`` call, a
parameter annotated ``: Config``, ``GLOBAL_CONFIG``, or ``.cfg`` on a
known Runtime producer (``get_runtime()``/``current_runtime_or_none()``).
DataContext likewise via ``DataContext.get_current()``/``get_context()``.

Project-scoped: knob definitions are collected from every scanned file
that defines a class named ``Config`` or ``DataContext`` with annotated
fields; the dead-knob direction counts reads across the whole scanned
set (attribute reads, ``"knob"`` string keys, ``RAY_TPU_KNOB`` env
names). Dead-knob checking therefore only makes sense when the scan
includes the knobs' consumers — lint the package root, not config.py
alone.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from ray_tpu.devtools.lint.astutil import FuncNode, dotted_name, walk_scope
from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

_CONFIG_CLASSES = ("Config", "DataContext")
_RUNTIME_PRODUCERS = {"get_runtime", "current_runtime_or_none"}
_CONFIG_PRODUCERS = {"Config", "Config.load", "Config.from_json"}


def _class_fields(tree: ast.AST, path: str) -> Dict[str, dict]:
    """{class_name: {"fields": {name: line}, "methods": set, "path": ..}}"""
    out: Dict[str, dict] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) \
                or node.name not in _CONFIG_CLASSES:
            continue
        fields: Dict[str, int] = {}
        methods: Set[str] = set()
        for st in node.body:
            if isinstance(st, ast.AnnAssign) \
                    and isinstance(st.target, ast.Name) \
                    and not st.target.id.startswith("_"):
                fields[st.target.id] = st.lineno
            elif isinstance(st, FuncNode):
                methods.add(st.name)
            elif isinstance(st, ast.Assign):
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        methods.add(t.id)  # class attrs are not knobs
        if fields:
            out[node.name] = {"fields": fields, "methods": methods,
                              "path": path, "line": node.lineno}
    return out


def _ann_is(ann, cls: str) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Name):
        return ann.id == cls
    if isinstance(ann, ast.Attribute):
        return ann.attr == cls
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip("'\"") == cls
    return False


def _ctx_producer_names(tree: ast.AST) -> Set[str]:
    """Bare names that really produce a DataContext in this file.

    ``get_context`` is a popular function name (train sessions have
    their own), so a bare call only counts when the file imports it
    from the data-execution context module — or shadows nothing and
    defines DataContext itself.
    """
    names: Set[str] = set()
    defines_ctx = any(isinstance(n, ast.ClassDef)
                      and n.name == "DataContext"
                      for n in ast.walk(tree))
    local_defs = {n.name for n in ast.walk(tree) if isinstance(n, FuncNode)}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and ("execution" in node.module
                     or node.module.endswith("context")):
            for alias in node.names:
                if alias.name == "get_context":
                    names.add(alias.asname or alias.name)
    if defines_ctx:
        names.add("get_context")
    else:
        names -= local_defs  # a same-named local def shadows the import
    return names


class _FileTyper(ast.NodeVisitor):
    """Per-file, flow-insensitive binding of names to Config/DataContext.

    Tracks plain names (``cfg = Config.load()``), self attributes
    (``self.cfg = cfg`` where cfg is a typed param), and runtime-typed
    names so ``r.cfg`` resolves.
    """

    def __init__(self, ctx_producers: Set[str] = frozenset()):
        self.ctx_producers = set(ctx_producers)
        self.config_names: Set[str] = set()     # bare names -> Config
        self.ctx_names: Set[str] = set()        # bare names -> DataContext
        self.runtime_names: Set[str] = set()    # bare names -> Runtime
        self.self_config_attrs: Set[str] = set()  # "self.<attr>" -> Config
        self.accesses: List[Tuple[str, ast.Attribute]] = []  # (cls, node)

    # -- typing helpers --------------------------------------------------
    def _expr_type(self, node: ast.AST) -> str:
        """'' | 'Config' | 'DataContext' | 'Runtime' for an expression."""
        if isinstance(node, ast.Name):
            if node.id == "GLOBAL_CONFIG" or node.id in self.config_names:
                return "Config"
            if node.id in self.ctx_names:
                return "DataContext"
            if node.id in self.runtime_names:
                return "Runtime"
            return ""
        if isinstance(node, ast.Attribute):
            if node.attr == "GLOBAL_CONFIG":
                return "Config"
            base = self._expr_type(node.value)
            if base == "Runtime" and node.attr == "cfg":
                return "Config"
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and f"self.{node.attr}" in self.self_config_attrs:
                return "Config"
            return ""
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            tail2 = ".".join(name.split(".")[-2:])
            tail1 = name.split(".")[-1]
            if name in _CONFIG_PRODUCERS or tail2 in _CONFIG_PRODUCERS:
                return "Config"
            if name == "DataContext.get_current" \
                    or tail2 == "DataContext.get_current" \
                    or name in self.ctx_producers:
                return "DataContext"
            if tail1 in _RUNTIME_PRODUCERS:
                return "Runtime"
            return ""
        if isinstance(node, ast.BoolOp):  # cfg = cfg or Config.load()
            for v in node.values:
                t = self._expr_type(v)
                if t:
                    return t
        return ""

    def _bind(self, target: ast.AST, typ: str):
        if not typ:
            return
        dest = {"Config": self.config_names,
                "DataContext": self.ctx_names,
                "Runtime": self.runtime_names}[typ]
        if isinstance(target, ast.Name):
            dest.add(target.id)
        elif typ == "Config" and isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            self.self_config_attrs.add(f"self.{target.attr}")

    # -- visitors --------------------------------------------------------
    def _visit_fn(self, node):
        for arg in (node.args.args + node.args.kwonlyargs
                    + node.args.posonlyargs):
            if _ann_is(arg.annotation, "Config"):
                self.config_names.add(arg.arg)
            elif _ann_is(arg.annotation, "DataContext"):
                self.ctx_names.add(arg.arg)
        self.generic_visit(node)

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Assign(self, node):
        typ = self._expr_type(node.value)
        for t in node.targets:
            self._bind(t, typ)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if _ann_is(node.annotation, "Config"):
            self._bind(node.target, "Config")
        elif _ann_is(node.annotation, "DataContext"):
            self._bind(node.target, "DataContext")
        elif node.value is not None:
            self._bind(node.target, self._expr_type(node.value))
        self.generic_visit(node)

    def visit_Attribute(self, node):
        base = self._expr_type(node.value)
        if base in ("Config", "DataContext"):
            self.accesses.append((base, node))
        self.generic_visit(node)


def _scope_filter(tree: ast.AST, typer: _FileTyper):
    """Drop accesses whose receiver root is an unannotated parameter of
    the enclosing function: the file-global name table is flow-
    insensitive, so ``def f(cfg: Config)`` must not type a *different*
    function's ``cfg`` parameter (RL configs reuse the name heavily).
    A param locally rebound from a typed producer stays typed."""
    owner = {}
    for fn in ast.walk(tree):
        if isinstance(fn, FuncNode):
            for sub in walk_scope(fn):
                owner[id(sub)] = fn

    def keep(access: Tuple[str, ast.Attribute]) -> bool:
        _, node = access
        root = node.value
        while isinstance(root, ast.Attribute):
            root = root.value
        if not isinstance(root, ast.Name) or root.id in ("self", "cls"):
            return True
        fn = owner.get(id(node))
        if fn is None:
            return True
        params = {a.arg: a for a in (fn.args.args + fn.args.kwonlyargs
                                     + fn.args.posonlyargs)}
        arg = params.get(root.id)
        if arg is None:
            return True
        if _ann_is(arg.annotation, "Config") \
                or _ann_is(arg.annotation, "DataContext"):
            return True
        for sub in walk_scope(fn):
            if isinstance(sub, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == root.id
                            for t in sub.targets) \
                    and typer._expr_type(sub.value):
                return True
        return False

    typer.accesses = [a for a in typer.accesses if keep(a)]


_TOKEN_ATTR = re.compile(r"\.(\w+)")
_TOKEN_STR = re.compile(r"['\"](\w+)['\"]")
_TOKEN_ENV = re.compile(r"RAY_TPU_(\w+)")


def extract_config(tree: ast.AST, source: str, path: str) -> dict:
    """The per-file half of the knob-drift analysis, JSON-able so the
    engine can cache it (summaries.py calls this into FileSummary.config).
    The cross-file aggregation lives in check_graph below."""
    typer = _FileTyper(_ctx_producer_names(tree))
    # two passes so use-before-def bindings (methods defined above
    # __init__) still resolve
    typer.visit(tree)
    typer.accesses.clear()
    typer.visit(tree)
    _scope_filter(tree, typer)

    classes = {
        cls: {"fields": info["fields"],
              "methods": sorted(info["methods"]), "line": info["line"]}
        for cls, info in _class_fields(tree, path).items()}

    # self.<attr> loads inside a config class defined here — the class
    # mediates access for its callers (e.g. DataContext.resolve_policy)
    self_reads: Dict[str, List[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in classes:
            reads = {sub.attr for sub in ast.walk(node)
                     if isinstance(sub, ast.Attribute)
                     and isinstance(sub.ctx, ast.Load)
                     and isinstance(sub.value, ast.Name)
                     and sub.value.id == "self"}
            if reads:
                self_reads[node.name] = sorted(reads)

    # knob-shaped tokens for the untyped-receiver/string-key/env-var
    # fallback: `.knob`, "knob", RAY_TPU_KNOB (env tails keep their
    # `_`-split prefixes so RAY_TPU_FOO_BAR still reads knob `foo`)
    tokens = set(_TOKEN_ATTR.findall(source))
    tokens.update(_TOKEN_STR.findall(source))
    for env in _TOKEN_ENV.findall(source):
        parts = env.lower().split("_")
        for i in range(1, len(parts) + 1):
            tokens.add("_".join(parts[:i]))

    return {
        "classes": classes,
        "accesses": [[cls, node.attr, node.lineno, node.col_offset]
                     for cls, node in typer.accesses],
        "self_reads": self_reads,
        "tokens": sorted(tokens),
    }


@register
class ConfigKnobDrift(Rule):
    id = "config-knob-drift"
    doc = ("Config/DataContext attribute referenced but never defined, "
           "or defined but never read anywhere in the scanned tree")
    hint = ("define the knob on the config class, or delete/wire the "
            "dead knob")
    scope = "graph"

    def check_graph(self, graph):
        classes: Dict[str, dict] = {}
        for fs in graph.files:
            for cls, info in fs.config.get("classes", {}).items():
                if cls in classes:
                    # two definitions (e.g. fixtures): merge fields so
                    # neither side false-positives the other's knobs
                    classes[cls]["fields"].update(info["fields"])
                    classes[cls]["methods"] |= set(info["methods"])
                else:
                    classes[cls] = {"fields": dict(info["fields"]),
                                    "methods": set(info["methods"]),
                                    "path": fs.path, "line": info["line"]}
        if not classes:
            return

        read_fields: Dict[str, Set[str]] = {c: set() for c in classes}
        findings: List[Finding] = []

        for fs in graph.files:
            cfg = fs.config
            for cls, reads in cfg.get("self_reads", {}).items():
                if cls in classes and fs.path == classes[cls]["path"]:
                    read_fields[cls] |= \
                        set(reads) & set(classes[cls]["fields"])
            for cls, attr, line, col in cfg.get("accesses", []):
                if cls not in classes:
                    continue
                info = classes[cls]
                if attr in info["fields"]:
                    read_fields[cls].add(attr)
                elif attr not in info["methods"] \
                        and not attr.startswith("_"):
                    findings.append(Finding(
                        rule=self.id, path=fs.path, line=line, col=col,
                        message=f"{cls}.{attr} is read here but {cls} "
                                "defines no such knob",
                        hint="add the field to the config class (typo?)"))
            tokens = set(cfg.get("tokens", []))
            for cls, info in classes.items():
                if fs.path == info["path"]:
                    continue  # the defining file doesn't count
                for f in info["fields"]:
                    if f not in read_fields[cls] and f in tokens:
                        read_fields[cls].add(f)

        for cls, info in classes.items():
            for f, line in sorted(info["fields"].items()):
                if f not in read_fields[cls]:
                    findings.append(Finding(
                        rule=self.id, path=info["path"], line=line, col=4,
                        message=f"{cls}.{f} is defined but nothing in the "
                                "scanned tree reads it — tuning it is a "
                                "silent no-op",
                        hint="wire the knob into the code path it "
                             "documents, or delete it"))
        yield from findings
