"""mesh-axis-consistency: literal axis names must be declared somewhere.

An axis name in a ``PartitionSpec``, an ``axis_name=`` kwarg, or a
``lax.psum``-family call that no mesh in the project declares is almost
always a typo — and JAX does not make it loud. ``logical_to_mesh``
drops axes whose mesh size is 1 (``mesh.shape.get(axis, 1)``), so
``P("fdsp")`` on an fsdp mesh silently *replicates* the tensor every
rank instead of sharding it: no error, no speedup, 8x the HBM.

The declared-axes universe is the union over the whole project —
module constants like ``AXIS_ORDER = ("dp", "pp", ...)``, literal
``Mesh(...)``/``make_mesh(...)`` axis tuples, and ``MeshSpec``/
``DCNSpec`` keyword names. The rule stays silent when that universe is
empty (a tree that never declares a mesh has nothing to check against),
which also keeps single-file fixtures self-contained.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

_CTX_WORDS = {
    "partition-spec": "PartitionSpec",
    "axis-kwarg": "axis_name= kwarg",
    "axis-default": "axis_name default",
    "lax-collective": "lax collective",
    "axis-query": "axis query",
    "rules-value": "ShardingRules value",
}


@register
class MeshAxisConsistency(Rule):
    id = "mesh-axis-consistency"
    doc = ("literal axis name not declared by any mesh/preset in the "
           "project — unknown axes silently replicate instead of "
           "sharding (mesh.shape treats them as size 1)")
    hint = ("fix the axis-name typo, or declare the axis on a mesh "
            "(AXIS_ORDER / Mesh(..., axis_names=...) / MeshSpec kwarg)")
    scope = "graph"

    def check_graph(self, graph):
        declared = graph.declared_axes()
        if not declared:
            return
        universe = sorted(declared)
        for nid, s in sorted(graph.functions.items()):
            path = graph.fn_path.get(nid, "?")
            seen = set()
            for ax, line, col, ctx in (s.spmd or {}).get("axis_uses", []):
                if ax in declared or (ax, line, col) in seen:
                    continue
                seen.add((ax, line, col))
                where = _CTX_WORDS.get(ctx, ctx)
                yield Finding(
                    rule=self.id, path=path, line=line, col=col,
                    message=(f"axis {ax!r} in a {where} is not declared "
                             f"by any mesh in the project (declared: "
                             f"{', '.join(universe)}) — an unknown axis "
                             "silently replicates instead of sharding"),
                    hint=self.hint,
                    spmd={"axis": ax, "context": ctx,
                          "declared_axes": universe})
