"""blocking-in-async: synchronous blocking calls inside ``async def``.

One blocking call on the event loop stalls every coroutine sharing it —
the class of bug behind the collective mailbox's "must stay off the
event loop" workaround. Matches exact call chains (``time.sleep``,
``ray_tpu.get``, ``runtime.get`` ...), not any ``.get`` tail, so RPC
client lookups like ``runtime.pool.get(addr)`` don't false-positive.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.astutil import dotted_name, walk_scope
from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

# Exact dotted chains (after stripping a leading ``self.``) that block
# the calling thread. Conservative by design: aliases the analyzer can't
# see stay unflagged rather than spraying false positives.
_BLOCKING = {
    "time.sleep",
    "ray_tpu.get", "ray_tpu.wait",
    "runtime.get", "runtime.wait",
    "rt.get", "rt.wait",
    "_runtime.get", "_runtime.wait",
    "_rt.get", "_rt.wait",
}

_ASYNC_ALTERNATIVE = {
    "time.sleep": "await asyncio.sleep(...)",
}


@register
class BlockingInAsync(Rule):
    id = "blocking-in-async"
    doc = ("blocking call (time.sleep / runtime.get / object-store read) "
           "inside an async def body stalls the whole event loop")
    hint = ("use the async equivalent, or push the blocking call to a "
            "thread with loop.run_in_executor")

    def check(self, parsed):
        for fn in ast.walk(parsed.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name.startswith("self."):
                    name = name[len("self."):]
                if name in _BLOCKING:
                    alt = _ASYNC_ALTERNATIVE.get(
                        name, "an awaitable API / run_in_executor")
                    yield Finding(
                        rule=self.id, path=parsed.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"blocking {name}(...) inside async def "
                                f"{fn.name} blocks the event loop",
                        hint=f"replace with {alt}")
