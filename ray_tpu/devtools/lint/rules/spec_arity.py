"""spec-arity: shard_map in_specs/out_specs must match the wrapped fn.

``shard_map`` zips ``in_specs`` against the wrapped function's
positional arguments; a 3-spec tuple over a 2-argument function fails
at trace time on TPU pods — long after the CI that ran on CPU passed,
because the mismatch only trips once a real mesh is attached. The
out_specs side is worse: a tuple out_specs against a non-tuple return
(or the wrong tuple width) reshards garbage.

Only literal tuple/list specs are checked (a variable or pytree-prefix
spec is recorded as arity -1 and skipped), and functions taking
``*args`` are exempt — the rule under-approximates rather than guess.
Covers both the decorator form (``@sharded_jit(in_specs=...)`` on the
function itself) and the call form (``jax.shard_map(f, in_specs=...)``)
with the target resolved through the project call graph.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register


def _pos_range(params):
    """(min, max) acceptable spec count for a params record, accounting
    for a leading self/cls; (None, None) when *args makes it unknowable."""
    n_pos, n_required, has_varargs, first = params
    if has_varargs:
        return None, None
    skip = 1 if first in ("self", "cls") else 0
    return max(0, n_required - skip), max(0, n_pos - skip)


@register
class SpecArity(Rule):
    id = "spec-arity"
    doc = ("shard_map/sharded_jit in_specs arity disagrees with the "
           "wrapped function's signature (or out_specs with its return "
           "arity) — fails at trace time only once a real mesh attaches")
    hint = ("give every mapped positional argument exactly one spec in "
            "in_specs, and match out_specs to the returned tuple shape")
    scope = "graph"

    def check_graph(self, graph):
        for nid, s in sorted(graph.functions.items()):
            module = nid.split(":", 1)[0]
            path = graph.fn_path.get(nid, "?")
            sp = s.spmd or {}

            jd = sp.get("jit")
            if jd and jd.get("kind") in ("sharded_jit", "shard_map"):
                yield from self._compare(
                    path, jd["line"], f"@{jd['kind']} on {s.qualname}",
                    jd.get("in_arity", -1), jd.get("out_arity", -1),
                    sp.get("params"), sp.get("returns", -1))

            for kind, target, line, in_a, out_a in sp.get("jit_wraps", []):
                if in_a < 0 and out_a < 0:
                    continue
                callee = graph.resolve_call(module, s.cls, target)
                if callee is None:
                    continue
                cs = graph.functions.get(callee)
                if cs is None:
                    continue
                csp = cs.spmd or {}
                yield from self._compare(
                    path, line, f"{kind}({target}, ...)",
                    in_a, out_a, csp.get("params"),
                    csp.get("returns", -1))

    def _compare(self, path, line, what, in_a, out_a, params, returns):
        if params is None:
            return
        lo, hi = _pos_range(params)
        facts = {"in_specs": in_a, "out_specs": out_a,
                 "params": list(params), "returns": returns}
        if in_a >= 0 and lo is not None and not (lo <= in_a <= hi):
            takes = str(hi) if lo == hi else f"{lo}..{hi}"
            yield Finding(
                rule=self.id, path=path, line=line, col=0,
                message=(f"{what}: in_specs has {in_a} spec(s) but the "
                         f"wrapped function takes {takes} positional "
                         "argument(s)"),
                hint=self.hint, spmd=facts)
        if out_a >= 0 and returns >= 0 and out_a != returns:
            yield Finding(
                rule=self.id, path=path, line=line, col=0,
                message=(f"{what}: out_specs has {out_a} spec(s) but "
                         f"the wrapped function returns a "
                         f"{returns}-tuple"),
                hint=self.hint, spmd=facts)
