"""useless-suppression: a disable directive that suppresses nothing.

Suppressions are debt: each one is a spot where the linter was
overruled, justified by a comment that rots as the code under it
changes. When the rule stops firing there — the hazard was fixed, the
code moved, the directive's line drifted — the stale directive keeps
silently masking any *future* violation on that line. This meta-rule
audits the inventory after every other rule has run: a ``disable=``
whose named rule produces no raw finding on a covered line (or
``disable-file=`` whose rule never fires anywhere in the file) is
itself flagged.

Only directives naming rules active in this run are judged — running a
single rule in isolation must not condemn directives for the rules
that didn't run. ``disable=all`` is judged against *any* finding at
the covered lines.

This rule is ``file_wide_only``: a line-level
``# raylint: disable=useless-suppression`` cannot hide its own audit
(and is itself useless-by-construction, so it gets flagged). Fixture
and generated files can opt out with
``# raylint: disable-file=useless-suppression``.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register


@register
class UselessSuppression(Rule):
    id = "useless-suppression"
    doc = ("a `# raylint: disable=` directive whose rule no longer "
           "fires on the covered lines — stale debt masking future "
           "violations")
    hint = ("delete the directive; if the rule was recently split or "
            "renamed, update the rule id instead")
    scope = "report"
    severity = "warn"
    file_wide_only = True

    def check_report(self, parsed_files, findings, active_ids):
        # raw (pre-suppression) findings indexed per file
        by_path = {}
        for f in findings:
            if f.rule == self.id:
                continue
            by_path.setdefault(f.path, []).append(f)
        for pf in parsed_files:
            hits = by_path.get(pf.path, [])
            lines_hit = {}
            for f in hits:
                lines_hit.setdefault(f.line, set()).add(f.rule)
            for d in pf.suppressions.directives:
                judged = {r for r in d["rules"]
                          if r in active_ids or r == "all"}
                if d["file_level"]:
                    # disable-file=useless-suppression is the designated
                    # opt-out — it is not judged against itself
                    judged.discard(self.id)
                if not judged:
                    continue  # names only rules not active in this run
                for rule in sorted(judged):
                    if d["file_level"]:
                        used = any(
                            (rule == "all" and hits)
                            or f.rule == rule for f in hits)
                    else:
                        used = any(
                            rule in lines_hit.get(ln, ())
                            or (rule == "all" and ln in lines_hit)
                            for ln in d["covered"])
                    if used:
                        continue
                    kind = ("disable-file" if d["file_level"]
                            else "disable")
                    yield Finding(
                        rule=self.id, path=pf.path, line=d["line"],
                        col=0,
                        message=(f"`# raylint: {kind}={rule}` "
                                 "suppresses nothing — the rule does "
                                 "not fire "
                                 + ("anywhere in this file"
                                    if d["file_level"] else
                                    "on the covered line(s)")),
                        hint=self.hint)
