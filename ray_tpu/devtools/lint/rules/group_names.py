"""hardcoded-group-name: elastic re-form paths must not pin group names.

Elastic remediation re-forms collective groups under a generation-
suffixed name (``collective.generation_name("train", 2)`` ->
``"train@g2"``) precisely so that stragglers from the old gang cannot
rendezvous with the new one. A call reachable from a re-form path that
passes a *literal* group name bypasses that: after the first
remediation it targets the generation-0 group, which no longer exists —
the op blocks until the collective timeout and the freshly healed gang
wedges again.

Roots are functions that look like elastic/remediation entry points
(module or qualname mentioning elastic/reform/remediate); from each
root the rule walks the call graph and flags any literal group-name
argument on a host-collective call. Names built dynamically —
f-strings, variables, ``generation_name(...)`` results — are invisible
to the extract by construction, so they never fire.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

_ROOT_WORDS = ("elastic", "reform", "remediat")


def _is_elastic_root(nid: str, s) -> bool:
    module = nid.split(":", 1)[0].lower()
    qual = s.qualname.lower()
    return any(w in module for w in _ROOT_WORDS) \
        or any(w in qual for w in _ROOT_WORDS) \
        or "elastic" in (s.cls or "").lower()


@register
class HardcodedGroupName(Rule):
    id = "hardcoded-group-name"
    doc = ("literal collective group name reachable from an elastic "
           "re-form path — re-formed groups are generation-suffixed, so "
           "the hardcoded name targets a group that no longer exists")
    hint = ("thread the group name through from the caller and build it "
            "with collective.generation_name(group, generation)")
    scope = "graph"

    def check_graph(self, graph):
        reported = set()
        for nid, s in sorted(graph.functions.items()):
            if not _is_elastic_root(nid, s):
                continue
            for reach_nid, _path in graph.reach(nid):
                rs = graph.functions.get(reach_nid)
                if rs is None:
                    continue
                for op, name, line, col in (rs.spmd or {}).get(
                        "group_literals", []):
                    site = (reach_nid, line, col)
                    if site in reported:
                        continue
                    reported.add(site)
                    via = "" if reach_nid == nid else \
                        f" (reached from {s.qualname})"
                    yield Finding(
                        rule=self.id,
                        path=graph.fn_path.get(reach_nid, "?"),
                        line=line, col=col,
                        message=(f"{op}(...) uses hardcoded group name "
                                 f"{name!r} on an elastic re-form path"
                                 f"{via} — after remediation the live "
                                 "group is generation-suffixed and this "
                                 "call targets the dead one"),
                        hint=self.hint,
                        spmd={"group": name, "op": op,
                              "elastic_root": s.qualname})
