"""host-effect-in-jit: host-side effects inside a jit-compiled body.

A jitted function's Python body runs once, at trace time. A host
effect written there — ``ray_tpu.get``, ``time.sleep``, a wall-clock
read, a metrics RPC, a host collective — either executes exactly once
and bakes its result into the compiled program (wall-clock reads,
metric increments that silently stop counting) or turns every
dispatch into a host round-trip that defeats the compilation entirely
(blocking gets inside a shard_map). Both are bugs that CPU-backed
tests cannot see: the trace executes eagerly there, so behavior only
changes on a real TPU backend.

Jit roots are functions carrying a jit/sharded_jit/shard_map decorator
plus the resolvable targets of ``jax.jit(f)`` / ``shard_map(f, ...)``
call sites. Reachability is deliberately shallow (depth 3): helpers
called from a jitted body are usually device code, and the short
horizon keeps a resolution mistake from spraying findings.
"""

from __future__ import annotations

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

_DEPTH = 3
_BLOCK_WORDS = {"get": "blocking ray_tpu.get", "wait": "blocking wait",
                "sleep": "time.sleep", "join": "thread join",
                "cond-wait": "condition wait"}


@register
class HostEffectInJit(Rule):
    id = "host-effect-in-jit"
    doc = ("host-side effect (blocking get/wait/sleep, wall-clock read, "
           "metric RPC, host collective) inside a jit-compiled body — "
           "runs at trace time only, or blocks every dispatch")
    hint = ("move the host effect outside the jitted function and pass "
            "its result in as an argument (or return data to log)")
    scope = "graph"

    def _jit_roots(self, graph):
        roots = {}
        for nid, s in sorted(graph.functions.items()):
            sp = s.spmd or {}
            if sp.get("jit"):
                roots.setdefault(nid, s.qualname)
            module = nid.split(":", 1)[0]
            for kind, target, _line, _ia, _oa in sp.get("jit_wraps", []):
                callee = graph.resolve_call(module, s.cls, target)
                if callee is not None and callee in graph.functions:
                    roots.setdefault(
                        callee, graph.functions[callee].qualname)
        return roots

    def check_graph(self, graph):
        reported = set()
        for root, root_name in sorted(self._jit_roots(graph).items()):
            for nid, _path in graph.reach(root, depth=_DEPTH):
                s = graph.functions.get(nid)
                if s is None:
                    continue
                path = graph.fn_path.get(nid, "?")
                inside = "" if nid == root else \
                    f" (called from jitted {root_name})"
                sites = []
                for b in s.blocking:
                    what = _BLOCK_WORDS.get(b["kind"], b["kind"])
                    sites.append((b["line"], b["col"],
                                  f"{what} ({b['name']})", b["kind"]))
                for op, line, col in s.collectives:
                    sites.append((line, col,
                                  f"host collective {op}(...)",
                                  "host-collective"))
                for kind, name, line, col in (s.spmd or {}).get(
                        "host_effects", []):
                    what = ("wall-clock read" if kind == "wall-clock"
                            else "metric RPC")
                    sites.append((line, col, f"{what} ({name})", kind))
                for line, col, what, kind in sites:
                    key = (nid, line, col)
                    if key in reported:
                        continue
                    reported.add(key)
                    yield Finding(
                        rule=self.id, path=path, line=line, col=col,
                        message=(f"{what} inside the jit-compiled body "
                                 f"of {root_name}{inside} — executes at "
                                 "trace time only (or blocks every "
                                 "dispatch)"),
                        hint=self.hint,
                        spmd={"jit_root": root_name, "effect": kind})
