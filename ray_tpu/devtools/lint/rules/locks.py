"""Static lockset analysis: lock-order-inversion + blocking-under-lock.

Both rules run over the project lock-acquisition graph built from
function summaries. A *section* is a ``with <lock>:`` body (or a bare
``.acquire()``) whose receiver resolves to a known ``threading.Lock /
RLock / Condition`` site — module-level or ``self.<attr>`` assigned in
the owning class. Unresolvable receivers are dropped: better to miss a
hand-rolled lock wrapper than to spray false positives through the
tier-1 gate.

**lock-order-inversion**: edge A -> B whenever B is acquired inside a
section holding A — directly, via a second ``with`` item, or through
any function transitively reachable from the section body (depth-
capped). A cycle in that graph means two threads can each hold one
lock and wait for the other. A self-edge on a non-reentrant ``Lock``
(re-acquiring the lock you hold, possibly through a helper) is the
degenerate single-thread deadlock — the ``_DEVICE_LOCK`` XLA-rendezvous
hang fixed in PR 6 was this class.

**blocking-under-lock**: a section body that performs — directly or
transitively — a blocking operation: ``ray_tpu.get``/``wait``, a
thread ``join``, a ``Condition.wait`` on a *different* condition, or a
``time.sleep`` of a second or more. Every other thread touching that
lock now inherits the stall (watchdogs fire, actors miss heartbeats).
Call sites whose callee cannot be resolved are ignored; genuinely-safe
sites go in ``ALLOW_UNDER_LOCK`` with a written justification or get a
line suppression.
"""

from __future__ import annotations

import fnmatch
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register

# (lock key glob, blocked-op glob) pairs that are known-safe, each with
# a reason. Keep this list short and justified — it is the rule-level
# escape hatch the per-line suppression syntax cannot express (e.g. a
# pattern that recurs across call paths through one lock).
ALLOW_UNDER_LOCK: List[Tuple[str, str, str]] = [
    # collective mailbox: cv.wait with per-round timeout IS the rendezvous
    # protocol; the cv lock is released while waiting by definition.
    ("*._cv", "*.wait", "condition-wait releases its own lock"),
]


def _allowed(lock_key: str, op_name: str) -> bool:
    return any(fnmatch.fnmatch(lock_key, lk) and fnmatch.fnmatch(op_name,
                                                                 opk)
               for lk, opk, _ in ALLOW_UNDER_LOCK)


class _Section:
    __slots__ = ("lock", "kind", "nid", "summary", "raw")

    def __init__(self, lock, kind, nid, summary, raw):
        self.lock, self.kind = lock, kind
        self.nid, self.summary, self.raw = nid, summary, raw


def _sections(graph):
    """Resolved lock sections across the project."""
    out: List[_Section] = []
    for nid, s in graph.functions.items():
        module = nid.split(":", 1)[0]
        for raw in s.lock_sections:
            key, kind = graph.resolve_lock(module, s.cls, raw["expr"])
            if key:
                out.append(_Section(key, kind, nid, s, raw))
    return out


def _contains(section_raw: dict, line: int) -> bool:
    lo, hi = section_raw["span"]
    return lo <= line <= hi


def _locks_reachable(graph, nid: str, cache: Dict[str, Dict[str, list]]
                     ) -> Dict[str, list]:
    """{lock key: call path} for every lock some function reachable
    from ``nid`` acquires (anywhere in its body)."""
    if nid in cache:
        return cache[nid]
    out: Dict[str, list] = {}
    for rnid, path in graph.reach(nid, include_start=False):
        rs = graph.summary(rnid)
        if rs is None:
            continue
        rmod = rnid.split(":", 1)[0]
        for raw in rs.lock_sections:
            key, _ = graph.resolve_lock(rmod, rs.cls, raw["expr"])
            if key and key not in out:
                out[key] = path + [[f"{rs.qualname}:{raw['line']}",
                                    raw["line"], raw["col"]]]
    cache[nid] = out
    return out


def _blocking_reachable(graph, nid: str,
                        cache: Dict[str, List[tuple]]) -> List[tuple]:
    """Blocking ops in functions reachable from ``nid``:
    [(op dict, owning summary, call path)]."""
    if nid in cache:
        return cache[nid]
    out: List[tuple] = []
    for rnid, path in graph.reach(nid, include_start=False):
        rs = graph.summary(rnid)
        if rs is None:
            continue
        for b in rs.blocking:
            if _is_blocking(graph, rnid, rs, b):
                out.append((b, rs, path))
    cache[nid] = out
    return out


def _is_blocking(graph, nid: str, s, b: dict) -> bool:
    """Is this recorded op a real stall? (filters the heuristics)."""
    kind = b["kind"]
    if kind in ("get", "wait"):
        return True
    if kind == "sleep":
        secs = b.get("seconds")
        return secs is not None and secs >= 1.0
    if kind == "join":
        recv = b.get("recv", "")
        parts = recv.split(".")
        module = nid.split(":", 1)[0]
        if parts[0] == "self" and len(parts) == 2 and s.cls:
            tag, _, _ = graph.attr_type(s.cls, parts[1],
                                        prefer_module=module)
            return tag == "thread"
        if len(parts) == 1:
            return s.local_types.get(parts[0], "") == "thread"
        return False
    return False   # cond-wait handled at the section level


def _cond_wait_key(graph, nid: str, s, b: dict) -> Optional[str]:
    """Lock key of a cond-wait receiver, None if unresolved."""
    module = nid.split(":", 1)[0]
    key, kind = graph.resolve_lock(module, s.cls, b.get("recv", ""))
    return key if kind == "cond" else None


@register
class LockOrderInversion(Rule):
    id = "lock-order-inversion"
    doc = ("cyclic lock-acquisition order (A under B here, B under A "
           "elsewhere) or re-acquiring a non-reentrant Lock you hold")
    hint = ("acquire the locks in one global order everywhere, or "
            "collapse them into a single lock")
    scope = "graph"

    def check_graph(self, graph):
        sections = _sections(graph)
        lock_cache: Dict[str, Dict[str, list]] = {}
        # edges[(A, B)] = (path, line, col) proving B is taken under A
        edges: Dict[Tuple[str, str], Tuple[str, int, int, str]] = {}

        by_fn: Dict[str, List[_Section]] = {}
        for sec in sections:
            by_fn.setdefault(sec.nid, []).append(sec)

        for sec in sections:
            if sec.raw.get("acquire_only"):
                continue
            holder = sec.lock
            # (a) nested sections in the same function body
            for other in by_fn.get(sec.nid, []):
                if other is sec:
                    continue
                same_group = other.raw.get("group") is not None and \
                    other.raw.get("group") == sec.raw.get("group")
                if same_group:
                    if other.raw.get("group_idx", 0) > \
                            sec.raw.get("group_idx", 0):
                        edges.setdefault((holder, other.lock), (
                            sec.nid, other.raw["line"],
                            other.raw["col"], "multi-item with"))
                    continue
                if _contains(sec.raw, other.raw["line"]):
                    edges.setdefault((holder, other.lock), (
                        sec.nid, other.raw["line"], other.raw["col"],
                        "nested acquisition"))
            # (b) locks acquired by anything called from the body
            for name, line, col in sec.summary.calls:
                if not _contains(sec.raw, line):
                    continue
                callee = graph.resolve_call(sec.nid.split(":", 1)[0],
                                            sec.summary.cls, name)
                if callee is None:
                    continue
                inner = dict(_locks_reachable(graph, callee, lock_cache))
                own = graph.summary(callee)
                if own is not None:
                    cmod = callee.split(":", 1)[0]
                    for raw in own.lock_sections:
                        key, _ = graph.resolve_lock(cmod, own.cls,
                                                    raw["expr"])
                        if key and key not in inner:
                            inner[key] = [[name, line, col]]
                for key, path in inner.items():
                    edges.setdefault((holder, key), (
                        sec.nid, line, col,
                        f"via {name}(...)"))

        # self-edge on a non-reentrant lock = immediate deadlock
        kinds = {sec.lock: sec.kind for sec in sections}
        reported: Set[Tuple[str, ...]] = set()
        for (a, b), (nid, line, col, how) in sorted(edges.items()):
            if a == b and kinds.get(a) == "lock":
                key = ("self", a, nid, line)
                if key in reported:
                    continue
                reported.add(key)
                yield Finding(
                    rule=self.id, path=graph.fn_path.get(nid, "?"),
                    line=line, col=col,
                    message=(f"non-reentrant lock {a} re-acquired while "
                             f"already held ({how}) — single-thread "
                             "deadlock"),
                    hint="use RLock, or split the locked helper from "
                         "the locking entry point")

        # cycles of length >= 2 over distinct locks
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            if a != b:
                adj.setdefault(a, set()).add(b)

        def find_cycle(start: str) -> Optional[List[str]]:
            stack = [(start, [start])]
            seen = set()
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start:
                        return path + [start]
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append((nxt, path + [nxt]))
            return None

        for start in sorted(adj):
            cyc = find_cycle(start)
            if cyc is None:
                continue
            canon = tuple(sorted(set(cyc)))
            if canon in reported:
                continue
            reported.add(canon)
            a, b = cyc[0], cyc[1]
            nid, line, col, how = edges[(a, b)]
            yield Finding(
                rule=self.id, path=graph.fn_path.get(nid, "?"),
                line=line, col=col,
                message=("lock acquisition order cycle: "
                         + " -> ".join(cyc) + f" ({how}); two threads "
                         "taking opposite ends deadlock"),
                hint=self.hint)


@register
class BlockingUnderLock(Rule):
    id = "blocking-under-lock"
    doc = ("RPC get/wait, thread join, foreign Condition.wait, or "
           "long sleep while holding a lock — every contender stalls")
    hint = ("move the blocking call off-lock (snapshot state under the "
            "lock, block outside), or justify via ALLOW_UNDER_LOCK / "
            "a line suppression")
    scope = "graph"

    def check_graph(self, graph):
        blocking_cache: Dict[str, List[tuple]] = {}
        reported: Set[Tuple[str, int, str]] = set()

        for sec in _sections(graph):
            if sec.raw.get("acquire_only"):
                continue
            s, nid = sec.summary, sec.nid
            module = nid.split(":", 1)[0]

            # direct blocking ops inside the body
            for b in s.blocking:
                if not _contains(sec.raw, b["line"]):
                    continue
                if b["kind"] == "cond-wait":
                    ckey = _cond_wait_key(graph, nid, s, b)
                    if ckey is None or ckey == sec.lock:
                        continue   # waiting on the section's own cv
                    if _allowed(sec.lock, b["name"]):
                        continue
                    op_desc = f"{b['name']}(...) on foreign condition"
                elif _is_blocking(graph, nid, s, b):
                    if _allowed(sec.lock, b["name"]):
                        continue
                    op_desc = f"{b['name']}(...)"
                else:
                    continue
                key = (nid, b["line"], sec.lock)
                if key in reported:
                    continue
                reported.add(key)
                yield Finding(
                    rule=self.id, path=graph.fn_path.get(nid, "?"),
                    line=b["line"], col=b["col"],
                    message=(f"blocking {op_desc} while holding "
                             f"{sec.lock} — all contenders stall for "
                             "the full call"),
                    hint=self.hint)

            # blocking ops reached through calls made inside the body
            for name, line, col in s.calls:
                if not _contains(sec.raw, line):
                    continue
                callee = graph.resolve_call(module, s.cls, name)
                if callee is None:
                    continue
                hits = list(_blocking_reachable(graph, callee,
                                                blocking_cache))
                inner = graph.summary(callee)
                if inner is not None:
                    hits = [(b, inner, []) for b in inner.blocking
                            if _is_blocking(graph, callee, inner, b)] \
                        + hits
                for b, owner, path in hits:
                    if _allowed(sec.lock, b["name"]):
                        continue
                    key = (nid, line, sec.lock)
                    if key in reported:
                        continue
                    reported.add(key)
                    chain = " -> ".join([name] + [p[0] for p in path])
                    yield Finding(
                        rule=self.id,
                        path=graph.fn_path.get(nid, "?"),
                        line=line, col=col,
                        message=(f"call under {sec.lock} reaches "
                                 f"blocking {b['name']}(...) in "
                                 f"{owner.qualname} ({chain}) — the "
                                 "lock is held across the stall"),
                        hint=self.hint)
                    break
