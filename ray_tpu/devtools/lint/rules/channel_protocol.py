"""channel-protocol: compiled-graph / standing-channel lifecycle misuse.

The compiled-DAG layer (ray_tpu/dag) trades per-call dispatch for
standing channels, which buys a protocol the type system does not
enforce:

- ``execute()`` after ``teardown()`` raises at runtime ("CompiledDAG
  has been torn down") — statically visible when both happen on the
  same receiver in one straight-line block.
- ``put``/``enqueue``/``write`` after ``close()`` on the same channel
  silently drops or raises depending on the transport — same shape.
- a class that compiles a standing graph (``self.x = dag.
  experimental_compile()``) but whose shutdown path never calls
  ``self.x.teardown()`` leaks the channels and the pinned actors of
  every instance (the router's drop-compiled/drain dance exists
  precisely because of this).
- the KV-handoff lifecycle (serve/kv_transfer.py) rides the same
  protocol: ``export()`` after the exporter's ``close()`` raises (the
  pins are already withdrawn), and ``adopt()`` after the standing
  decode channel's ``teardown()``/``close()`` resolves refs whose
  primaries may already be unpinned — both are ordering errors, same
  shape as put-after-close.

Statement-order checks use the (block, idx) identity the summaries
record — two ops only pair when they sit in the same statement list,
so ``if err: dag.teardown()`` followed by a normal-path ``execute()``
does not false-positive. The shutdown-path check walks the class's
own methods through the call graph: any teardown reachable from any
shutdown-ish method (``shutdown``/``stop``/``close``/``__exit__``...)
satisfies it.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register
from ray_tpu.devtools.lint.summaries import SHUTDOWN_METHODS

_TERMINAL = {"teardown": ("execute", "adopt"),
             "close": ("put", "enqueue", "write", "export", "adopt")}


@register
class ChannelProtocol(Rule):
    id = "channel-protocol"
    doc = ("compiled-graph misuse: execute() after teardown(), enqueue "
           "on a closed channel, or a compiled graph no shutdown path "
           "tears down")
    hint = ("teardown()/close() must be the last op on a channel; give "
            "the owning class a shutdown path that tears the graph down")
    scope = "graph"

    def check_graph(self, graph):
        yield from self._sequencing(graph)
        yield from self._shutdown_paths(graph)

    # -- execute-after-teardown / put-after-close ------------------------
    def _sequencing(self, graph):
        for nid, s in sorted(graph.functions.items()):
            # (recv, block) -> [(op, line, col, idx)]
            seq: Dict[Tuple[str, int], List[Tuple[str, int, int, int]]]
            seq = {}
            for op in s.channel_ops:
                seq.setdefault((op["recv"], op["block"]), []).append(
                    (op["op"], op["line"], op["col"], op["idx"]))
            for (recv, _), ops in sorted(seq.items()):
                ops.sort(key=lambda t: t[3])
                for term, banned in _TERMINAL.items():
                    term_idx = next((t[3] for t in ops if t[0] == term),
                                    None)
                    if term_idx is None:
                        continue
                    for op, line, col, idx in ops:
                        if op in banned and idx > term_idx:
                            yield Finding(
                                rule=self.id,
                                path=graph.fn_path.get(nid, "?"),
                                line=line, col=col,
                                message=(f"{recv}.{op}(...) after "
                                         f"{recv}.{term}() in "
                                         f"{s.qualname} — the channel "
                                         "is already released"),
                                hint=self.hint)

    # -- compiled graph without a teardown on shutdown paths -------------
    def _shutdown_paths(self, graph):
        path_of_module = {fs.module: fs.path for fs in graph.files}
        for cls_name, (module, cs) in sorted(graph.classes.items()):
            compiled = sorted(a for a, tag in cs.attr_types.items()
                              if tag == "compiled")
            if not compiled:
                continue
            shutdownish = [m for m in cs.methods
                           if m in SHUTDOWN_METHODS]
            if not shutdownish:
                continue   # no shutdown path to audit
            torn: Set[str] = set()
            for m in shutdownish:
                nid = graph.method_node(cls_name, m,
                                        prefer_module=module)
                if nid is None:
                    continue
                for rnid, _ in graph.reach(nid):
                    rs = graph.summary(rnid)
                    if rs is None:
                        continue
                    for op in rs.channel_ops:
                        if op["op"] == "teardown":
                            recv = op["recv"].split(".")
                            if recv[0] == "self" and len(recv) == 2:
                                torn.add(recv[1])
                            else:
                                # torn down via a local alias — accept
                                # any teardown in the class's own reach
                                torn.update(compiled)
            for attr in compiled:
                if attr in torn:
                    continue
                yield Finding(
                    rule=self.id,
                    path=path_of_module.get(module, "?"),
                    line=cs.attr_lines.get(attr, cs.line), col=0,
                    message=(f"{cls_name}.{attr} holds a compiled graph "
                             f"but no shutdown path ("
                             f"{', '.join(sorted(shutdownish))}) ever "
                             f"calls its teardown() — standing channels "
                             "and pinned actors leak"),
                    hint=self.hint)
