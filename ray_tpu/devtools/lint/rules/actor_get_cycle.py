"""actor-get-cycle: blocking get whose remote target can call back.

The canonical distributed deadlock: actor A's method blocks in
``ray_tpu.get(b.f.remote(...))`` while B.f (or anything B.f blocks on
in turn) makes a blocking get back into actor A. A is single-threaded
and stuck inside the get, so the call-back can never be served — both
actors hang until a timeout reaps the job (the serve-controller
``_stop`` hang fixed in PR 5 was exactly this shape).

Detection is interprocedural over the project call graph:

1. From every actor method, collect blocking-get sites reachable
   through local helper calls (same class / same module, depth-capped).
2. Each get site names its remote targets (``recv.meth.remote``).
   Receivers resolve through class-attribute and local-variable actor
   types (``self._h = Worker.remote(...)``); an unresolved receiver
   falls back to the actor classes that define the method name, but
   only when that resolution is unique — an ambiguous method name is
   dropped rather than guessed.
3. Follow the blocking-get edges actor-to-actor. If the closure can
   re-enter the originating actor class (including a self-get), the
   originating get site is flagged with the full cycle path.

``get`` on a self-owned handle (``ray_tpu.get(self._self_handle.m
.remote())``) is degenerate but caught by the same machinery.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.findings import Finding
from ray_tpu.devtools.lint.registry import Rule, register


def _resolve_targets(graph, summary, module: str,
                     targets: List[dict]) -> List[Tuple[str, str]]:
    """[(actor class name, method)] a get site can block on."""
    out: List[Tuple[str, str]] = []
    for t in targets:
        recv, method = t["recv"], t["method"]
        cls_name: Optional[str] = None
        parts = recv.split(".")
        if parts[0] == "self" and len(parts) == 2 and summary.cls:
            tag, _, _ = graph.attr_type(summary.cls, parts[1],
                                        prefer_module=module)
            if tag.startswith("actor:"):
                cls_name = tag.split(":", 1)[1]
        elif len(parts) == 1:
            tag = summary.local_types.get(parts[0], "")
            if tag.startswith("actor:"):
                cls_name = tag.split(":", 1)[1]
        if cls_name is None:
            # name-based fallback: unique actor class defining the method
            owners = graph.actor_methods.get(method, [])
            if len(owners) == 1:
                cls_name = owners[0]
        if cls_name is not None:
            hit = graph.class_of(cls_name, prefer_module=module)
            if hit is not None and hit[1].is_actor \
                    and method in hit[1].methods:
                out.append((cls_name, method))
    return out


def _get_edges(graph, start_nid: str):
    """Blocking-get sites reachable from ``start_nid`` through local
    calls: [(site dict, site node id, summary, call path, targets)]."""
    out = []
    for nid, path in graph.reach(start_nid):
        s = graph.summary(nid)
        if s is None:
            continue
        module = nid.split(":", 1)[0]
        for b in s.blocking:
            if b["kind"] != "get" or not b.get("targets"):
                continue
            resolved = _resolve_targets(graph, s, module, b["targets"])
            if resolved:
                out.append((b, nid, s, path, resolved))
    return out


@register
class ActorGetCycle(Rule):
    id = "actor-get-cycle"
    doc = ("blocking ray_tpu.get inside an actor method whose remote "
           "target can call back into the same actor — distributed "
           "deadlock")
    hint = ("break the cycle: make one side async (await / callback), "
            "or move the blocking get off the actor's main thread")
    scope = "graph"

    def check_graph(self, graph):
        # cache: actor class -> outgoing blocking-get target classes
        edges_of: Dict[str, Set[str]] = {}

        def class_edges(cls_name: str) -> Set[str]:
            if cls_name in edges_of:
                return edges_of[cls_name]
            edges_of[cls_name] = set()   # cycle guard during build
            hit = graph.class_of(cls_name)
            if hit is None:
                return set()
            mod, cs = hit
            targets: Set[str] = set()
            for m in cs.methods:
                nid = graph.method_node(cls_name, m, prefer_module=mod)
                if nid is None:
                    continue
                for edge in _get_edges(graph, nid):
                    targets.update(c for c, _ in edge[4])
            edges_of[cls_name] = targets
            return targets

        def reaches(src_cls: str, dst_cls: str,
                    seen: Set[str]) -> Optional[List[str]]:
            """Chain of actor classes from src to dst over blocking-get
            edges, or None."""
            if src_cls == dst_cls:
                return [src_cls]
            if src_cls in seen:
                return None
            seen.add(src_cls)
            for nxt in sorted(class_edges(src_cls)):
                sub = reaches(nxt, dst_cls, seen)
                if sub is not None:
                    return [src_cls] + sub
            return None

        reported: Set[Tuple[str, int]] = set()
        for nid, s in sorted(graph.functions.items()):
            if not s.is_actor or not s.cls:
                continue
            qual_head = s.qualname.split(".")[0]
            if qual_head != s.cls:
                continue   # nested class oddities: skip
            for b, site_nid, where, path, resolved in _get_edges(graph,
                                                                 nid):
                for target_cls, target_meth in resolved:
                    chain = reaches(target_cls, s.cls, set())
                    if chain is None:
                        continue
                    site = (where.qualname, b["line"])
                    if site in reported:
                        continue
                    reported.add(site)
                    via = "" if not path else (
                        " (reached via " +
                        " -> ".join(p[0] for p in path) + ")")
                    loop = " -> ".join([s.cls] + chain)
                    yield Finding(
                        rule=self.id,
                        path=graph.fn_path.get(site_nid, where.qualname),
                        line=b["line"], col=b["col"],
                        message=(f"blocking {b['name']}(...) on "
                                 f"{target_cls}.{target_meth} inside "
                                 f"actor method {s.cls}."
                                 f"{s.qualname.split('.', 1)[1]} can "
                                 f"deadlock: {loop}{via}"),
                        hint=self.hint)
                    break
