"""ray_tpu: a TPU-native distributed computing framework.

The capabilities of Ray (tasks, actors, distributed objects, placement
groups, ML libraries) re-designed for TPU clusters: JAX/XLA/pjit/Pallas for
compute, XLA collectives over ICI/DCN for the SPMD plane, a native
shared-memory object store for the host data plane, and slice-aware
scheduling.

Public API (reference: python/ray/_private/worker.py — init:1108, get:2410,
put:2519, wait:2582, kill:2748, cancel:2779, remote:2925):

    import ray_tpu

    ray_tpu.init()

    @ray_tpu.remote
    def f(x): return x * 2

    ray_tpu.get(f.remote(2))  # -> 4

Subpackages (imported lazily; none of them load jax at import time):
    ray_tpu.parallel — device mesh + DP/FSDP/TP/PP/SP/EP sharding presets
    ray_tpu.models   — flagship model zoo (llama, gpt2, moe)
    ray_tpu.ops      — Pallas kernels (flash/ring attention, ...)
    ray_tpu.train    — distributed Trainer (JaxTrainer)
    ray_tpu.data     — streaming datasets
    ray_tpu.tune     — hyperparameter search
    ray_tpu.serve    — model serving
    ray_tpu.rl       — RL (TPU learner / CPU rollout split)
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.core import runtime as _rt
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor, method
from ray_tpu.core.common import (ObjectRef, ObjectRefGenerator,
                                 ResourceSet)
from ray_tpu.core.config import Config
from ray_tpu.core.ids import JobID
from ray_tpu.core.node import (detect_tpu_chips, new_session_dir, start_gcs,
                               start_nodelet)
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core import status as exceptions

__version__ = "0.1.0"

_init_lock = threading.Lock()
_session: Optional[dict] = None


def is_initialized() -> bool:
    return _rt.current_runtime_or_none() is not None


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: str = "default",
         ignore_reinit_error: bool = False,
         runtime_env: Optional[Dict[str, Any]] = None,
         _system_config: Optional[Dict[str, Any]] = None) -> dict:
    """Start (or connect to) a ray_tpu cluster.

    address=None starts a new local cluster (gcs + one nodelet) unless
    RAY_TPU_ADDRESS is set (the launcher's exec/attach/submit export it —
    ref: ray.init() honoring RAY_ADDRESS); address="host:port" connects
    to an existing GCS.
    ref: worker.py:1108 init / node.py:1148 start_head_processes.
    """
    if address is None:
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    global _session
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return dict(_session or {})
            raise RuntimeError("ray_tpu.init() already called")
        cfg = Config.load(_system_config)
        procs = []
        if address is None:
            session_dir = new_session_dir()
            gcs_proc, gcs_addr = start_gcs(session_dir, cfg)
            procs.append(gcs_proc)
            res = dict(resources or {})
            res.setdefault("CPU", float(num_cpus if num_cpus is not None
                                        else (os.cpu_count() or 1)))
            chips = num_tpus if num_tpus is not None else detect_tpu_chips()
            if chips:
                # cfg.chip_resource lets heterogeneous fleets rename the
                # logical chip resource (e.g. "TPU_V5E") cluster-wide
                res.setdefault(cfg.chip_resource, float(chips))
            nodelet_proc, nodelet_addr, node_id_hex, store_name = start_nodelet(
                session_dir, cfg, gcs_addr, resources=res)
            procs.append(nodelet_proc)
        else:
            session_dir = os.environ.get("RAY_TPU_SESSION_DIR", new_session_dir())
            h, p = address.rsplit(":", 1)
            gcs_addr = (h, int(p))
            # find a local nodelet via GCS (pick any alive node on 127.0.0.1;
            # multi-host drivers would match on hostname)
            import asyncio

            from ray_tpu.core.rpc import RpcClient

            async def _nodes():
                c = RpcClient(*gcs_addr)
                try:
                    return await c.call("get_nodes", timeout=cfg.rpc_connect_timeout_s)
                finally:
                    await c.close()
            nodes = asyncio.run(_nodes())
            alive = [n for n in nodes if n.alive]
            if not alive:
                raise RuntimeError(f"no alive nodes at {address}")
            nodelet_addr = alive[0].nodelet_addr
            store_name = alive[0].store_name
            node_id_hex = alive[0].node_id.hex()

        job_id = JobID.from_random()
        runtime = _rt.Runtime(cfg, gcs_addr, nodelet_addr, store_name, job_id,
                              mode="driver", node_id=node_id_hex)
        _rt.set_runtime(runtime)
        runtime.start()
        if runtime_env:
            # Job-level env: merged into every submitted task/actor spec
            # that doesn't set its own (ref: job_config runtime_env).
            from ray_tpu import runtime_env as _renv

            runtime.default_runtime_env = _renv.resolve_uris(runtime,
                                                             runtime_env)
        runtime.gcs_call("add_job", job_id=job_id, driver_addr=runtime.address.addr,
                         meta={"namespace": namespace, "pid": os.getpid()})
        if cfg.log_to_driver:
            runtime.subscribe_logs()
        _session = {
            "address": f"{gcs_addr[0]}:{gcs_addr[1]}",
            "session_dir": session_dir,
            "node_addr": nodelet_addr,
            "namespace": namespace,
            "procs": procs,
            "job_id": job_id,
        }
        atexit.register(shutdown)
        return dict(_session)


def shutdown():
    """Stop the runtime; kill daemons we started (ref: ray.shutdown)."""
    global _session
    with _init_lock:
        runtime = _rt.current_runtime_or_none()
        if runtime is not None:
            try:
                runtime.flush_task_events()
                runtime.gcs_call("finish_job", job_id=runtime.job_id, rpc_timeout=2.0)
            except Exception:
                pass
            runtime.shutdown()
        if _session:
            for p in _session.get("procs", []):
                try:
                    p.terminate()
                except Exception:
                    pass
            for p in _session.get("procs", []):
                try:
                    p.wait(timeout=3)
                except Exception:
                    try:
                        p.kill()
                    except Exception:
                        pass
            _session = None
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


def remote(*args, **options):
    """@ray_tpu.remote / @ray_tpu.remote(**options) on functions or classes."""
    def make(obj):
        if isinstance(obj, type):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and callable(args[0]) and not options:
        return make(args[0])
    if args:
        raise TypeError("@ray_tpu.remote takes keyword options only")
    return make


def put(value: Any) -> ObjectRef:
    return _rt.get_runtime().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    runtime = _rt.get_runtime()
    if isinstance(refs, ObjectRef):
        single, refs = True, [refs]
    elif isinstance(refs, (list, tuple)):
        single, refs = False, list(refs)
    else:
        raise TypeError(f"ray_tpu.get expects ObjectRef or list, got {type(refs)}")
    t0 = time.monotonic()
    out = runtime.get(refs, timeout=timeout)
    elapsed = time.monotonic() - t0
    warn_s = runtime.cfg.get_timeout_warn_s
    if warn_s > 0 and elapsed > warn_s:
        # ref: ray's "waiting for X seconds" driver warning — a slow get
        # usually means a lost/hung producer, not a slow transfer
        import logging

        logging.getLogger(__name__).warning(
            "ray_tpu.get of %d ref(s) blocked for %.1fs "
            "(get_timeout_warn_s=%.1fs); pass timeout= to bound waits",
            len(refs), elapsed, warn_s)
    return out[0] if single else out


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None):
    if not isinstance(refs, (list, tuple)):
        raise TypeError("ray_tpu.wait expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return _rt.get_runtime().wait(list(refs), num_returns=num_returns,
                                  timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    _rt.get_runtime().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = False):
    """Cancel a task (ref: ray.cancel): queued tasks are dropped; an
    executing task gets KeyboardInterrupt (force=True kills its worker).
    The ref's get raises TaskCancelledError. Finished tasks: no-op."""
    _rt.get_runtime().cancel(ref, force=force, recursive=recursive)


class RuntimeContext:
    """Where am I running? (ref: python/ray/runtime_context.py
    RuntimeContext — get_node_id/get_job_id/get_task_id/get_worker_id).
    Snapshot at call time; fetch a fresh one per query."""

    def __init__(self, rt):
        self.node_id = rt.node_id
        self.job_id = rt.job_id.hex()
        self.worker_id = (rt.worker_id.hex()
                          if isinstance(rt.worker_id, bytes)
                          else str(rt.worker_id))
        # exec-context only: None outside a task, like the reference's
        # get_task_id (get_current_task_id falls back to the synthetic
        # driver task id, which is for put-id spaces, not user context)
        tid = getattr(rt._exec_ctx, "task_id", None)
        self.task_id = tid.hex() if tid is not None else None
        # per-execution-context (thread/asyncio-task), NOT per-process:
        # lane-packed actors share a process, so this is the only
        # reliable "which actor am I" (ref: RuntimeContext.get_actor_id)
        aid = getattr(rt._exec_ctx, "actor_id", None)
        self.actor_id = aid.hex() if aid is not None else None
        self.worker_mode = rt.mode

    def get_node_id(self) -> str:
        return self.node_id

    def get_actor_id(self):
        """Id of the actor whose method is executing, else None."""
        return self.actor_id

    def get_job_id(self) -> str:
        return self.job_id

    def get_task_id(self):
        return self.task_id

    def get_worker_id(self) -> str:
        return self.worker_id


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_rt.get_runtime())


def nodes() -> List[dict]:
    out = []
    for n in _rt.get_runtime().gcs_call("get_nodes"):
        out.append({"NodeID": n.node_id.hex(), "Alive": n.alive,
                    "Resources": n.resources_total.quantities,
                    "Labels": n.labels, "NodeletAddress": n.nodelet_addr,
                    "StoreName": n.store_name})
    return out


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in _rt.get_runtime().gcs_call("get_nodes"):
        if not n.alive:
            continue
        for k, v in n.resources_total.quantities.items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for _, q in _rt.get_runtime().gcs_call("get_available_resources").items():
        for k, v in q.items():
            total[k] = total.get(k, 0.0) + v
    return total


def _fanout_nodelets(method: str) -> Dict[str, dict]:
    """Call `method` on every alive nodelet; errors become {"error": ...}."""
    rt = _rt.get_runtime()
    out = {}
    for n in rt.gcs_call("get_nodes"):
        if not n.alive:
            continue
        try:
            out[n.node_id.hex()] = rt.node_call(n.nodelet_addr, method)
        except Exception as e:
            out[n.node_id.hex()] = {"error": str(e)}
    return out


def stack() -> Dict[str, dict]:
    """All-thread stack dumps from every worker on every alive node
    (ref: `ray stack` scripts.py:1789)."""
    return _fanout_nodelets("dump_worker_stacks")


def internal_stats() -> Dict[str, dict]:
    """Per-daemon handler counts/latency + event-loop lag
    (ref: event_stats.h instrumentation + per-daemon OpenCensus stats),
    plus this process's HBM device-tier occupancy."""
    rt = _rt.get_runtime()
    out = {"gcs": rt.gcs_call("internal_stats"),
           "driver": {"device_store": rt.device_store.stats()}}
    for nid, stats in _fanout_nodelets("internal_stats").items():
        out[f"nodelet:{nid[:12]}"] = stats
    return out


def timeline(limit: int = 1000, chrome: bool = False) -> List[dict]:
    """Recent task state transitions and tracing spans from the GCS
    task-event store (ref: `ray timeline` scripts.py:1835). Flushes the
    local TelemetryAgent first, so spans recorded just before the call
    are visible (read-your-writes). `chrome=True` returns the merged
    Chrome trace with per-worker lanes instead of raw events
    (observability/timeline.py) — json.dump it and load in
    chrome://tracing."""
    rt = _rt.get_runtime()
    rt.flush_task_events(wait=True)
    events = rt.gcs_call("list_task_events", limit=limit)
    if chrome:
        from ray_tpu.observability import chrome_trace

        return chrome_trace(events)
    return events


__all__ = [
    "init", "shutdown", "remote", "put", "get", "wait", "kill", "cancel",
    "get_runtime_context",
    "method", "get_actor", "nodes", "cluster_resources", "available_resources",
    "timeline", "stack", "internal_stats",
    "ObjectRef", "ObjectRefGenerator", "ActorHandle", "exceptions", "is_initialized",
    "__version__",
]
