"""Checkpoints: directory-backed, jax-pytree aware, URI-portable.

Reference: python/ray/air/checkpoint.py (dict/dir/URI morphable Checkpoint),
python/ray/air/_internal/remote_storage.py (cloud persistence) and Train's
TuneCheckpointManager. A Checkpoint is a directory; pytrees of jax arrays
are saved with orbax (tensorstore OCDBT — each process writes only its
addressable shards, so multi-host sharded state saves without gathering),
with treedef + non-array leaves pickled alongside. `to_uri`/`from_uri` morph
a checkpoint to/from remote storage (file:// memory:// gs:// s3://).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional

from ray_tpu.train import storage

_ARRAYS_SUBDIR = "arrays"
_AUX_FILE = "aux.pkl"
#: written last to a mirrored checkpoint URI — a remote copy without it is
#: a partial upload and is never restored from
_REMOTE_MARKER = ".ray_tpu_complete"


def _is_array_leaf(x: Any) -> bool:
    import numpy as np

    try:
        import jax

        if isinstance(x, jax.Array):
            return True
    except Exception:
        pass
    return isinstance(x, (np.ndarray, np.generic, int, float, bool, complex))


_checkpointer = None


def _get_checkpointer():
    """Singleton orbax StandardCheckpointer (async under the hood; callers
    wait via wait_until_finished)."""
    global _checkpointer
    if _checkpointer is None:
        import orbax.checkpoint as ocp

        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


class Checkpoint:
    def __init__(self, path: str, uri: Optional[str] = None):
        self.path = os.path.abspath(path)
        #: remote home of this checkpoint, when it has one — carried through
        #: pickling so a worker on another node can re-download (ref:
        #: air Checkpoint URI morphs)
        self.uri = uri

    # --- constructors -------------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any],
                  base_dir: Optional[str] = None) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ckpt_", dir=base_dir)
        with open(os.path.join(d, "payload.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        """ref: air/checkpoint.py Checkpoint.from_directory."""
        return cls(path)

    @classmethod
    def from_state(cls, state: Any, path: str,
                   async_save: bool = False) -> "Checkpoint":
        """Save a jax pytree (TrainState, params, ...) with orbax.

        Array leaves go through orbax StandardCheckpointer — sharded
        jax.Arrays are written shard-by-shard from their owning processes
        (works multi-host without any device_get/gather). Non-array leaves
        (callables, configs) plus the treedef are pickled to aux.pkl and
        re-attached at load. With async_save the tensorstore writes happen
        in the background; `wait()` (or the next save) joins them.
        """
        import jax

        os.makedirs(path, exist_ok=True)
        multiproc = jax.process_count() > 1
        primary = jax.process_index() == 0
        leaves, treedef = jax.tree_util.tree_flatten(state)

        def to_orbax(leaf) -> bool:
            if not _is_array_leaf(leaf):
                return False
            if not multiproc:
                return True
            # Multi-host: orbax can only serialize globally-sharded
            # jax.Arrays (each process writes its addressable shards).
            # Host-local leaves (scalars, numpy, single-device arrays —
            # replicated by construction in SPMD training) ride aux.pkl,
            # written by process 0 alone.
            return isinstance(leaf, jax.Array) and not leaf.is_fully_addressable

        arrays = {str(i): leaf for i, leaf in enumerate(leaves)
                  if to_orbax(leaf)}
        others = {i: _to_host(leaf)
                  for i, leaf in enumerate(leaves) if not to_orbax(leaf)}
        if primary:
            with open(os.path.join(path, _AUX_FILE), "wb") as f:
                pickle.dump({"treedef": treedef, "others": others,
                             "n": len(leaves), "ts": time.time(),
                             "procs": jax.process_count()}, f)
        arrays_dir = os.path.join(path, _ARRAYS_SUBDIR)
        if arrays:
            ckptr = _get_checkpointer()
            ckptr.wait_until_finished()  # serialize with a previous async save
            if primary and os.path.exists(arrays_dir):
                shutil.rmtree(arrays_dir)
            if multiproc:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("ray_tpu_ckpt_clean")
            ckptr.save(arrays_dir, arrays)
            if not async_save:
                ckptr.wait_until_finished()
        return cls(path)

    @classmethod
    def from_uri(cls, uri: str, local_dir: Optional[str] = None) -> "Checkpoint":
        """Download a checkpoint from remote storage
        (ref: air/checkpoint.py Checkpoint.from_uri)."""
        d = local_dir or tempfile.mkdtemp(prefix="ckpt_dl_")
        storage.download_from_uri(uri, d)
        return cls(d, uri=uri)

    # --- accessors ----------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        self._ensure_local()
        p = os.path.join(self.path, "payload.pkl")
        with open(p, "rb") as f:
            return pickle.load(f)

    def load_state(self, abstract_state: Any = None) -> Any:
        """Load the pytree saved by from_state.

        abstract_state: optional pytree of the same structure whose array
        leaves are jax.Arrays or jax.ShapeDtypeStruct (with `.sharding`
        set for a sharded restore) — orbax then places each restored array
        directly onto its target devices/sharding, which is how a
        multi-host TrainState comes back resident without a host round
        trip. Without it, arrays restore as host-local numpy-backed
        jax.Arrays (single-process only).
        """
        self._ensure_local()
        legacy = os.path.join(self.path, "state.pkl")
        if os.path.exists(legacy):  # pre-orbax format
            with open(legacy, "rb") as f:
                return pickle.load(f)
        import jax

        with open(os.path.join(self.path, _AUX_FILE), "rb") as f:
            aux = pickle.load(f)
        arrays_dir = os.path.join(self.path, _ARRAYS_SUBDIR)
        array_idx = [i for i in range(aux["n"]) if i not in aux["others"]]
        restored: Dict[str, Any] = {}
        if array_idx:
            ckptr = _get_checkpointer()
            ckptr.wait_until_finished()
            if abstract_state is not None:
                tleaves = jax.tree_util.tree_flatten(abstract_state)[0]
                if len(tleaves) != aux["n"]:
                    raise ValueError(
                        f"abstract_state has {len(tleaves)} leaves; "
                        f"checkpoint has {aux['n']}")
                target = {str(i): _abstract(tleaves[i]) for i in array_idx}
            else:
                # Host restore: build the target from orbax metadata with
                # single-device placement, so a checkpoint saved on a
                # bigger topology (16-device pod) still loads on this
                # process (e.g. the driver inspecting a result).
                sds = jax.sharding.SingleDeviceSharding(
                    jax.local_devices()[0])
                md = ckptr.metadata(arrays_dir)
                # orbax drift: newer versions return the item tree
                # directly instead of a CheckpointMetadata wrapper
                im = getattr(md, "item_metadata", md)
                meta = getattr(im, "tree", im)
                target = {k: jax.ShapeDtypeStruct(m.shape, m.dtype,
                                                  sharding=sds)
                          for k, m in meta.items()}
            restored = ckptr.restore(arrays_dir, target)
        leaves = [aux["others"][i] if i in aux["others"] else restored[str(i)]
                  for i in range(aux["n"])]
        return jax.tree_util.tree_unflatten(aux["treedef"], leaves)

    def to_directory(self) -> str:
        """ref: air/checkpoint.py Checkpoint.to_directory — a Checkpoint
        IS a directory here, so this is the identity accessor (plus a
        lazy download when the data still lives at the URI)."""
        self._ensure_local()
        return self.path

    def to_uri(self, uri: str, write_marker: bool = True) -> str:
        """Upload this checkpoint to remote storage
        (ref: air/checkpoint.py Checkpoint.to_uri). The completion marker
        is written last so a partial upload is never restored from;
        multi-rank mirrors pass write_marker=False and let rank 0 write it
        after a cross-host barrier."""
        self.wait()
        storage.upload_to_uri(self.path, uri)
        if write_marker:
            storage.touch_at_uri(storage.join_uri(uri, _REMOTE_MARKER))
        self.uri = uri
        return uri

    def wait(self) -> None:
        """Join any in-flight async orbax save for this process."""
        if _checkpointer is not None:
            _checkpointer.wait_until_finished()

    def exists(self) -> bool:
        return os.path.isdir(self.path) and bool(os.listdir(self.path))

    def saved_process_count(self) -> Optional[int]:
        """jax.process_count() recorded at save time (None: unreadable).
        An elastic resume compares this with the NEW world size — a
        multi-process orbax save restored at a different process count
        restores through abstract_state resharding, which is worth a
        remediation-event note for the operator timeline."""
        self._ensure_local()
        return _saved_procs(self.path)

    def _ensure_local(self) -> None:
        """Download from the URI when the local copy is absent or partial
        (a checkpoint pickled to a worker on another node, or a staging
        dir truncated by a crash). Lazy: runs at first read, so handles
        that merely pass a checkpoint around never transfer data. An
        flock serializes same-host readers racing to populate the same
        staging dir (note: the whole directory is fetched — selective
        per-shard reads straight from gs:// via tensorstore are a future
        optimization)."""
        if _complete(self.path) or not self.uri:
            return
        import fcntl

        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path + ".lock", "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            if _complete(self.path):  # loser of the race: winner populated
                return
            if not CheckpointManager._marked(self.uri):
                raise RuntimeError(
                    f"remote checkpoint {self.uri} has no completion "
                    f"marker (upload still running or died); refusing to "
                    f"restore a partial copy")
            storage.download_from_uri(self.uri, self.path)
            if not _complete(self.path):
                raise RuntimeError(
                    f"downloaded checkpoint from {self.uri} is incomplete")

    def __reduce__(self):
        return (Checkpoint, (self.path, self.uri))

    def __repr__(self):
        return f"Checkpoint({self.path})"


def _to_host(leaf: Any) -> Any:
    """Host (numpy) form of a host-local array leaf for pickling."""
    try:
        import jax

        if isinstance(leaf, jax.Array):
            import numpy as np

            return np.asarray(leaf)
    except Exception:
        pass
    return leaf


def _abstract(leaf: Any):
    """Abstract (shape/dtype/sharding) form of a target leaf for orbax."""
    import jax

    if isinstance(leaf, jax.ShapeDtypeStruct):
        return leaf
    if not hasattr(leaf, "shape"):  # python scalar target (int/float/bool)
        import numpy as np

        a = np.asarray(leaf)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)
    sharding = getattr(leaf, "sharding", None)
    return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sharding)


def _ckpt_index(name: str) -> Optional[int]:
    """Index of a checkpoint_NNNNNN dir name; None for anything else
    (crashed download temps, markers, user files)."""
    if not name.startswith("checkpoint_"):
        return None
    try:
        return int(name.split("_")[-1])
    except ValueError:
        return None


def _saved_procs(path: str) -> Optional[int]:
    """process_count recorded at save time; None when unreadable."""
    try:
        with open(os.path.join(path, _AUX_FILE), "rb") as f:
            return pickle.load(f).get("procs", 1)
    except Exception:
        # legacy pickle / payload checkpoints are single-process by nature
        if (os.path.exists(os.path.join(path, "state.pkl"))
                or os.path.exists(os.path.join(path, "payload.pkl"))):
            return 1
        return None


def _complete(path: str) -> bool:
    """True when `path` holds a complete checkpoint: legacy/payload formats,
    or aux.pkl plus (when array leaves exist) a committed orbax dir —
    orbax's own tmp-dir+rename makes the arrays dir presence equivalent to
    a committed save, so a crash mid-write never passes this check."""
    if not os.path.isdir(path):
        return False
    if (os.path.exists(os.path.join(path, "state.pkl"))
            or os.path.exists(os.path.join(path, "payload.pkl"))):
        return True
    aux_path = os.path.join(path, _AUX_FILE)
    if not os.path.exists(aux_path):
        return False
    try:
        with open(aux_path, "rb") as f:
            aux = pickle.load(f)
    except Exception:
        return False
    has_arrays = aux["n"] > len(aux["others"])
    return (not has_arrays
            or os.path.isdir(os.path.join(path, _ARRAYS_SUBDIR)))


class CheckpointManager:
    """Keeps the last N checkpoints in a run directory (ref:
    CheckpointConfig.num_to_keep + air checkpoint manager).

    run_dir may be a local path or a storage URI (file:// memory:// gs://
    s3://). With a URI, checkpoints are written to a deterministic local
    staging dir and mirrored to the URI on register(); latest() prefers
    local staging but falls back to downloading from the URI — so a
    restarted (or migrated) job resumes from cloud storage with no local
    state. ref: air _internal/remote_storage.py + SURVEY §5.4.
    """

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None):
        self.uri: Optional[str] = None
        if storage.is_uri(run_dir):
            self.uri = run_dir.rstrip("/")
            run_dir = storage.local_staging_dir(self.uri)
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        os.makedirs(run_dir, exist_ok=True)
        self._index = 0
        self._kept: list[str] = []
        self._mirror_q: Optional[Any] = None  # lazy upload-worker queue
        #: background mirror failures (persistence problems surfaced to
        #: callers that check; each is also logged when it happens)
        self.mirror_errors: List[str] = []
        self._load_existing()

    def _load_existing(self):
        names = {d for d in os.listdir(self.run_dir)
                 if _ckpt_index(d) is not None}
        if self.uri:
            names |= {d for d in storage.list_at_uri(self.uri)
                      if _ckpt_index(d) is not None}
        existing = sorted(names)
        self._kept = [os.path.join(self.run_dir, d) for d in existing]
        if existing:
            self._index = _ckpt_index(existing[-1]) + 1

    def new_dir(self, index: Optional[int] = None) -> str:
        """Next checkpoint dir. Pass `index` to pin a rank-agreed slot (a
        multi-host gang broadcasts rank 0's index and every rank MUST use
        exactly that slot — orbax's multihost barriers key on the
        directory path, so any rank diverging hangs the gang)."""
        if index is not None:
            self._index = index
        path = os.path.join(self.run_dir, f"checkpoint_{self._index:06d}")
        self._index += 1
        return path

    def register(self, path: str, primary: bool = True,
                 sync: bool = True):
        """Track a saved checkpoint; mirror it to the URI when set.

        In a multi-host gang every rank registers (each uploads the orbax
        shard files its process wrote — the remote dir is the merge), but
        only the primary writes the completion marker and performs remote
        eviction. The caller must barrier between non-primary and primary
        registration so the marker lands after all shards
        (session.report does). With sync=False (single-process mode) the
        upload+marker+remote-evict run on a background thread in FIFO
        order so the train loop isn't stalled for the transfer; call
        flush() to join (a checkpoint whose upload hasn't finished is
        protected by the marker gate in latest())."""
        evict: List[str] = []
        self._kept.append(path)
        if self.num_to_keep is not None:
            while len(self._kept) > self.num_to_keep:
                evict.append(self._kept.pop(0))

        def evict_local():
            for old in evict:
                shutil.rmtree(old, ignore_errors=True)

        def mirror():
            # eviction rides the mirror job so an evicted checkpoint's own
            # queued upload (FIFO-earlier) always finishes first
            Checkpoint(path).to_uri(
                storage.join_uri(self.uri, os.path.basename(path)),
                write_marker=primary)
            if primary:
                for old in evict:
                    storage.delete_at_uri(
                        storage.join_uri(self.uri, os.path.basename(old)))
            evict_local()

        if self.uri:
            if sync:
                mirror()
            else:
                self._enqueue_mirror(mirror)
        else:
            evict_local()

    def _enqueue_mirror(self, job) -> None:
        if self._mirror_q is None:
            import queue

            self._mirror_q = queue.Queue()

            def worker():
                while True:
                    j = self._mirror_q.get()
                    try:
                        if j is not None:
                            j()
                    except Exception as e:
                        # marker gate keeps the partial upload unrestorable,
                        # but the operator must hear persistence is failing
                        import logging

                        logging.getLogger(__name__).exception(
                            "background checkpoint mirror failed: %s", e)
                        self.mirror_errors.append(str(e))
                    finally:
                        self._mirror_q.task_done()

            import threading

            threading.Thread(target=worker, daemon=True,
                             name="ckpt-mirror").start()
        self._mirror_q.put(job)

    def flush(self) -> None:
        """Join all queued background mirrors."""
        if self._mirror_q is not None:
            self._mirror_q.join()

    def latest(self) -> Optional[Checkpoint]:
        for path in reversed(self._kept):
            remote = (storage.join_uri(self.uri, os.path.basename(path))
                      if self.uri else None)
            if _complete(path):
                # a marker-less mirror (crash mid-upload) must never be
                # downloaded by another node — heal it from the local
                # copy, but ONLY if that copy holds every shard (i.e. a
                # single-process save; one host of a collective save
                # can't certify the other hosts' shards)
                if remote and not self._marked(remote):
                    if _saved_procs(path) == 1:
                        try:
                            Checkpoint(path).to_uri(remote)
                        except Exception:
                            remote = None
                    else:
                        remote = None
                return Checkpoint(path, uri=remote)
            # Local copy absent or partial (crash mid-save/mid-download):
            # hand back a lazy remote-backed checkpoint — but only when
            # the upload finished (marker present). No data moves here;
            # load_state downloads on first read. Transient storage errors
            # skip to the next-older candidate instead of aborting the
            # caller's recovery loop.
            if remote and self._marked(remote):
                return Checkpoint(path, uri=remote)
        return None

    @staticmethod
    def _marked(remote: str) -> bool:
        try:
            return storage.exists_at_uri(
                storage.join_uri(remote, _REMOTE_MARKER))
        except Exception:
            # transient storage error: treat as unusable, caller moves on
            # to an older candidate instead of aborting its recovery loop
            return False
