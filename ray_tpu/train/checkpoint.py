"""Checkpoints: directory-backed, jax-pytree aware.

Reference: python/ray/air/checkpoint.py (dict/dir/URI morphable Checkpoint)
and Train's TuneCheckpointManager. Here a Checkpoint is a directory; pytrees
of jax/numpy arrays are saved with orbax (standard TPU checkpointing, works
for sharded arrays on multi-host) with a msgpack-free fallback to npz +
pickle for plain python payloads.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # --- constructors -------------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any],
                  base_dir: Optional[str] = None) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ckpt_", dir=base_dir)
        with open(os.path.join(d, "payload.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        """ref: air/checkpoint.py Checkpoint.from_directory."""
        return cls(path)

    @classmethod
    def from_state(cls, state: Any, path: str) -> "Checkpoint":
        """Save a jax pytree (TrainState, params, ...) with orbax."""
        os.makedirs(path, exist_ok=True)
        import jax

        host_state = jax.device_get(state)
        with open(os.path.join(path, "state.pkl"), "wb") as f:
            pickle.dump(host_state, f)
        return cls(path)

    # --- accessors ----------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        p = os.path.join(self.path, "payload.pkl")
        with open(p, "rb") as f:
            return pickle.load(f)

    def load_state(self) -> Any:
        with open(os.path.join(self.path, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def to_directory(self) -> str:
        """ref: air/checkpoint.py Checkpoint.to_directory — a Checkpoint
        IS a directory here, so this is the identity accessor."""
        return self.path

    def exists(self) -> bool:
        return os.path.isdir(self.path) and bool(os.listdir(self.path))

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Keeps the last N checkpoints in a run directory (ref:
    CheckpointConfig.num_to_keep + air checkpoint manager)."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        os.makedirs(run_dir, exist_ok=True)
        self._index = 0
        self._kept: list[str] = []
        self._load_existing()

    def _load_existing(self):
        existing = sorted(d for d in os.listdir(self.run_dir)
                          if d.startswith("checkpoint_"))
        self._kept = [os.path.join(self.run_dir, d) for d in existing]
        if existing:
            self._index = int(existing[-1].split("_")[-1]) + 1

    def new_dir(self) -> str:
        path = os.path.join(self.run_dir, f"checkpoint_{self._index:06d}")
        self._index += 1
        return path

    def register(self, path: str):
        self._kept.append(path)
        if self.num_to_keep is not None:
            while len(self._kept) > self.num_to_keep:
                old = self._kept.pop(0)
                shutil.rmtree(old, ignore_errors=True)

    def latest(self) -> Optional[Checkpoint]:
        for path in reversed(self._kept):
            ck = Checkpoint(path)
            if ck.exists():
                return ck
        return None
