"""JaxTrainer: fit() orchestration with failure recovery.

Reference: python/ray/train/data_parallel_trainer.py:58 +
base_trainer.py:570 fit + backend_executor.py failure handling
(get_with_failure_handling:564, _restart:625). One trainer class covers what
the reference splits into TorchTrainer/TensorflowTrainer/...: the framework
backend is always JAX, and parallelism comes from ScalingConfig.mesh/rules.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.status import ActorDiedError, ActorUnavailableError, TaskError
from ray_tpu.train import storage
from ray_tpu.train.backend import TensorflowBackend, TorchBackend
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[dict] = field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    # remediation audit trail when ScalingConfig.elastic drove the run
    # (run_tag, world size per generation, remediation events); None for
    # fixed-size runs — see ray_tpu/train/elastic.py
    elastic: Optional[Dict[str, Any]] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class JaxTrainer:
    #: collective bootstrap, overridable per subclass
    #  (ref: DataParallelTrainer's backend_config, data_parallel_trainer.py:58)
    backend_cls: type = None

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 backend=None):
        from ray_tpu.train.backend import JaxBackend

        self.loop = train_loop_per_worker
        self.config = train_loop_config or {}
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from = resume_from_checkpoint
        self.backend = backend or (self.backend_cls() if self.backend_cls
                                   else JaxBackend())

    def _run_dir(self) -> str:
        base = self.run_config.storage_path or os.path.expanduser(
            "~/ray_tpu_results")
        name = self.run_config.name or f"run_{int(time.time())}"
        if storage.is_uri(base):
            # remote run dir: CheckpointManager stages locally and mirrors
            # to the URI (ref: air RunConfig.storage_path cloud URIs)
            return storage.join_uri(base, name)
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path

    def fit(self) -> Result:
        if self.scaling.elastic is not None:
            # self-healing gang: health-plane-driven shrink/refill/grow
            # state machine instead of the whole-group retry loop below
            from ray_tpu.train.elastic import ElasticCoordinator

            return ElasticCoordinator(self).fit()
        run_dir = self._run_dir()
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        checkpoint = self.resume_from
        result = Result()
        while True:
            try:
                return self._fit_once(run_dir, checkpoint, result)
            except (ActorDiedError, ActorUnavailableError,
                    ray_tpu.exceptions.WorkerCrashedError,
                    ray_tpu.exceptions.NodeDiedError) as e:
                attempt += 1
                # resume from the newest checkpoint any attempt produced
                ck = (Checkpoint(result.metrics["_checkpoint"],
                                 uri=result.metrics.get("_checkpoint_uri"))
                      if result.metrics.get("_checkpoint") else checkpoint)
                checkpoint = _latest_checkpoint(run_dir) or ck
                if max_failures >= 0 and attempt > max_failures:
                    result.error = f"worker group failed: {e}"
                    return result

    def _fit_once(self, run_dir: str, checkpoint: Optional[Checkpoint],
                  result: Result) -> Result:
        group = WorkerGroup(self.scaling.num_workers,
                            self.scaling.worker_resources())
        try:
            # dataset shards: one DataIterator per rank (ref: session.py:901)
            shards: List[Dict[str, Any]] = _split_datasets(
                self.datasets, self.scaling.num_workers)
            coordinator = None
            if self.scaling.num_workers > 1 or self.backend.needs_coordinator:
                if getattr(self.backend, "needs_worker_addresses", False):
                    # TF_CONFIG-style backends need the FULL cluster spec:
                    # one reserved host:port per rank (each worker holds
                    # its reservation until its own setup() releases it)
                    infos = ray_tpu.get(
                        [w.host_info.remote() for w in group.workers])
                    self.backend.worker_addresses = [
                        f"{i['hostname']}:{i['free_port']}" for i in infos]
                    coordinator = self.backend.worker_addresses[0]
                else:
                    info = ray_tpu.get(group.workers[0].host_info.remote())
                    coordinator = f"{info['hostname']}:{info['free_port']}"
            setup_refs = [
                w.setup.remote(self.config, run_dir, self.scaling, checkpoint,
                               shards[i], coordinator,
                               self.run_config.checkpoint_config.num_to_keep,
                               self.backend)
                for i, w in enumerate(group.workers)]
            ray_tpu.get(setup_refs)
            run_refs = [w.run.remote(self.loop, self.config)
                        for w in group.workers]
            seen = 0
            hang_timeout = self.run_config.failure_config.hang_timeout_s
            startup_grace = self.run_config.failure_config.startup_grace_s
            last_progress = time.time()
            got_report = False
            while True:
                poll = ray_tpu.get(group.workers[0].poll.remote(seen))
                for r in poll["reports"]:
                    result.metrics_history.append(r)
                    result.metrics = r
                if poll["reports"]:
                    last_progress = time.time()
                    got_report = True
                seen += len(poll["reports"])
                if poll["error"]:
                    result.error = poll["error"]
                    break
                if poll["finished"]:
                    break
                # The no-progress clock effectively starts at the first
                # report: until then the worker is cold-starting (spawn +
                # jax import + first compile — repeated in full by every
                # restarted attempt), so the deadline is the startup
                # grace, not the steady-state report gap.
                limit = (hang_timeout if got_report
                         else max(hang_timeout or 0.0, startup_grace))
                if (hang_timeout is not None
                        and time.time() - last_progress > limit):
                    # stuck pjit program: a live-but-hung worker never
                    # raises, so the death-based retry path would wait
                    # forever — kill the group and surface a crash so
                    # fit()'s restart-from-checkpoint loop takes over
                    group.shutdown()
                    raise ray_tpu.exceptions.WorkerCrashedError(
                        f"train hang watchdog: no "
                        f"{'progress report' if got_report else 'first report'}"
                        f" for {limit}s (SURVEY hung-chip semantics: "
                        f"the group restarts from the last checkpoint)")
                ready, _ = ray_tpu.wait(run_refs, num_returns=len(run_refs),
                                        timeout=0.25)
                if len(ready) == len(run_refs):
                    # drain any last reports
                    poll = ray_tpu.get(group.workers[0].poll.remote(seen))
                    for r in poll["reports"]:
                        result.metrics_history.append(r)
                        result.metrics = r
                    break
            # surface user exceptions (TaskError) from any worker
            for ref in run_refs:
                try:
                    ray_tpu.get(ref, timeout=30)
                except TaskError as e:
                    result.error = str(e)
                    break
            if result.metrics.get("_checkpoint"):
                result.checkpoint = Checkpoint(
                    result.metrics["_checkpoint"],
                    uri=result.metrics.get("_checkpoint_uri"))
            else:
                result.checkpoint = _latest_checkpoint(run_dir)
            return result
        finally:
            group.shutdown()


class TorchTrainer(JaxTrainer):
    """Reference-parity torch trainer (ref: train/torch/torch_trainer.py):
    same orchestration, TorchBackend gloo process group instead of jax
    distributed bootstrap. User loops use torch.distributed +
    ray_tpu.train.prepare_model unchanged."""

    backend_cls = TorchBackend


class TensorflowTrainer(JaxTrainer):
    """Reference-parity TF trainer (ref: train/tensorflow/
    tensorflow_trainer.py + config.py:21,40): same orchestration,
    TF_CONFIG rendezvous exported per worker; user loops build
    tf.distribute.MultiWorkerMirroredStrategy unchanged."""

    backend_cls = TensorflowBackend


def _latest_checkpoint(run_dir: str) -> Optional[Checkpoint]:
    from ray_tpu.train.checkpoint import CheckpointManager

    return CheckpointManager(run_dir).latest()


def _split_datasets(datasets: Dict[str, Any], n: int) -> List[Dict[str, Any]]:
    shards: List[Dict[str, Any]] = [dict() for _ in range(n)]
    for name, ds in datasets.items():
        if hasattr(ds, "streaming_split"):
            its = ds.streaming_split(n)
            for i in range(n):
                shards[i][name] = its[i]
        else:
            for i in range(n):
                shards[i][name] = ds
    return shards
